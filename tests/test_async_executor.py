"""Async executor pipeline: non-blocking FetchHandles, sharding-aware
device prefetch, overlapped step-batched windows (README "Async
execution").

The contract under test: fetch_mode="async" returns handles that sync
ONLY on .numpy()/indexing (executor_fetch_sync_seconds stays at zero
until then), window prefetch overlaps window i+1's drain+stack+stage
with window i's device compute while preserving EOF-before-step
semantics bit-for-bit, and every background thread is reaped by
close()/exhaustion (the conftest fixture fails leaks suite-wide)."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor, optimizer
from paddle_tpu.fluid.executor import FetchHandle
from paddle_tpu.fluid.reader import DeviceStager, stage_feed


def _sgd_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _pyreader_program(B=4, D=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = layers.py_reader(capacity=8, shapes=[[B, D], [B, 1]],
                                  dtypes=["float32", "float32"])
        x, y = layers.read_file(reader)
        pred = layers.fc(x, 1, name="async_fc")
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, reader, loss


def _batches(n, B=4, D=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.rand(B, D).astype(np.float32),
             rng.rand(B, 1).astype(np.float32)) for _ in range(n)]


# -- FetchHandle semantics ----------------------------------------------------

def test_async_single_step_bit_identical_to_sync():
    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(8, 4).astype(np.float32),
              "label": rng.rand(8, 1).astype(np.float32)}
             for _ in range(3)]

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        ref = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        handles = [exe.run(main, feed=f, fetch_list=[loss],
                           fetch_mode="async")[0] for f in feeds]
    for r, h in zip(ref, handles):
        assert isinstance(h, FetchHandle)
        np.testing.assert_array_equal(np.asarray(r), h.numpy())


def test_async_batched_bit_identical_to_sync():
    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(1)
    k = 3
    xs = rng.rand(k, 8, 4).astype(np.float32)
    ys = rng.rand(k, 8, 1).astype(np.float32)

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (ref,) = exe.run(main, feed={"x": xs, "label": ys},
                         fetch_list=[loss], iters=k)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (h,) = exe.run(main, feed={"x": xs, "label": ys},
                       fetch_list=[loss], iters=k, fetch_mode="async")
    np.testing.assert_array_equal(np.asarray(ref), h.numpy())


def test_fetch_handle_api_and_sync_gating():
    """shape/dtype/repr/block_until_ready never sync; numpy/indexing/
    __array__/__float__ do, each recording in the fetch-sync
    histogram."""
    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    feed = {"x": np.ones((8, 4), np.float32),
            "label": np.ones((8, 1), np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        monitor.reset()
        (h,) = exe.run(main, feed=feed, fetch_list=[loss],
                       fetch_mode="async")
        hist = monitor.get_metric("executor_fetch_sync_seconds")
        assert h.shape == () or h.shape == (1,)
        assert h.dtype is not None
        assert "FetchHandle" in repr(h)
        assert h.block_until_ready() is h
        assert hist.count == 0, "metadata access must not sync"
        v = h.numpy()
        assert hist.count == 1
        assert np.isfinite(v).all()
        np.testing.assert_array_equal(np.asarray(h), v)
        assert float(h) == float(v.ravel()[0])
        assert hist.count >= 3  # each host materialization recorded


def test_run_hook_async_field():
    """Async runs add async=True to hook records; legacy records keep
    their exact key set (omit-when-default, like iters)."""
    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    feed = {"x": np.ones((8, 4), np.float32),
            "label": np.ones((8, 1), np.float32)}
    records = []
    fluid.register_run_hook(records.append)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
            exe.run(main, feed=feed, fetch_list=[loss],
                    fetch_mode="async")
    finally:
        fluid.unregister_run_hook(records.append)
    sync_rec, async_rec = records[-2], records[-1]
    assert "async" not in sync_rec
    assert async_rec["async"] is True


def test_fetch_mode_validation():
    exe = fluid.Executor()
    with pytest.raises(ValueError):
        exe.run(fluid.Program(), fetch_mode="banana")
    with pytest.raises(ValueError):
        exe.run(fluid.Program(), prefetch=True)  # iters=1
    main, startup, loss = _sgd_program()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(ValueError):
            # prefetch needs a py_reader-fed program
            exe.run(main, feed={"x": np.ones((2, 8, 4), np.float32),
                                "label": np.ones((2, 8, 1), np.float32)},
                    fetch_list=[loss], iters=2, prefetch=True)


# -- window prefetch ----------------------------------------------------------

def test_prefetch_trajectories_match_inline_across_epochs():
    """A prefetching loop produces the SAME losses, EOF points, and
    restart behavior as the inline (prefetch=False) loop — two full
    epochs, bit-identical."""
    def run_epochs(prefetch):
        main, startup, reader, loss = _pyreader_program()
        reader.decorate_tensor_provider(lambda: iter(_batches(6)))
        exe = fluid.Executor()
        out = []
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            for _ in range(2):
                reader.start()
                while True:
                    try:
                        (h,) = exe.run(main, fetch_list=[loss], iters=2,
                                       fetch_mode="async",
                                       prefetch=prefetch)
                    except fluid.core.EOFException:
                        reader.reset()
                        break
                    out.append(h.numpy().ravel())
        exe.close()
        return np.concatenate(out)

    np.testing.assert_array_equal(run_epochs(False), run_epochs(True))


def test_prefetch_eof_before_step_and_state_untouched():
    """5 batches, windows of k=2: the third window's prefetch underfills
    (1 batch left) — EOF must raise BEFORE any step runs, leaving the
    weights exactly as window 2 committed them."""
    main, startup, reader, loss = _pyreader_program()
    reader.decorate_tensor_provider(lambda: iter(_batches(5)))
    exe = fluid.Executor()
    wname = [v.name for v in main.list_vars()
             if v.persistable and ".w_" in v.name][0]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        reader.start()
        for _ in range(2):
            exe.run(main, fetch_list=[loss], iters=2, prefetch=True,
                    fetch_mode="async")
        w_before = np.asarray(scope.find_var(wname)).copy()
        with pytest.raises(fluid.core.EOFException):
            exe.run(main, fetch_list=[loss], iters=2, prefetch=True,
                    fetch_mode="async")
        np.testing.assert_array_equal(
            w_before, np.asarray(scope.find_var(wname)))
        # pass restarts deterministically after reset
        reader.start()
        (h,) = exe.run(main, fetch_list=[loss], iters=2, prefetch=True,
                       fetch_mode="async")
        assert np.isfinite(h.numpy()).all()
    exe.close()


def test_overlap_hit_miss_counters():
    main, startup, reader, loss = _pyreader_program()
    reader.decorate_tensor_provider(lambda: iter(_batches(6)))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        monitor.reset()
        reader.start()
        for _ in range(3):
            exe.run(main, fetch_list=[loss], iters=2, prefetch=True)
    exe.close()
    assert monitor.counter(
        "executor_window_overlap_miss_total").value == 1
    assert monitor.counter(
        "executor_window_overlap_hit_total").value == 2
    assert monitor.get_metric("executor_window_stall_seconds").count == 2


def test_window_prefetch_conflicts():
    """A pending prefetched window guards its readers: a single-step run
    or a different-iters batched run on the same readers is refused
    rather than silently mis-windowing batches; close() clears it."""
    main, startup, reader, loss = _pyreader_program()
    reader.decorate_tensor_provider(lambda: iter(_batches(10)))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        reader.start()
        exe.run(main, fetch_list=[loss], iters=2, prefetch=True)
        with pytest.raises(RuntimeError, match="prefetched"):
            exe.run(main, fetch_list=[loss])
        with pytest.raises(RuntimeError, match="mis-windowed"):
            exe.run(main, fetch_list=[loss], iters=3)
        exe.close()  # discards the pending window
        # single-step works again (prefetch state cleared)
        (lv,) = exe.run(main, fetch_list=[loss])
        assert np.isfinite(np.asarray(lv)).all()
    exe.close()


def test_no_leaked_threads_after_close():
    """close() must join the in-flight window prefetch thread even when
    the batched loop is abandoned mid-pass."""
    main, startup, reader, loss = _pyreader_program()
    reader.decorate_tensor_provider(lambda: iter(_batches(8)))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        reader.start()
        exe.run(main, fetch_list=[loss], iters=2, prefetch=True)
    exe.close()
    alive = [t.name for t in threading.enumerate()
             if t.is_alive() and t.name.startswith("paddle-window-prefetch")]
    assert not alive, alive


# -- sharding-aware staging ---------------------------------------------------

def test_feed_sharding_resolution():
    """CompiledProgram.feed_sharding: batch axis shards over 'dp' when
    divisible, replicates otherwise — the single source of truth the
    step wrappers and the stagers share."""
    import jax

    main, startup, loss = _sgd_program()
    from paddle_tpu.fluid import compiler

    cp = compiler.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=jax.devices()[:2])
    s = cp.feed_sharding(np.zeros((8, 3), np.float32))
    assert s.spec[0] == "dp"
    s = cp.feed_sharding(np.zeros((7, 3), np.float32))
    assert s.is_fully_replicated
    s = cp.feed_sharding(np.zeros((4, 8, 3), np.float32), batch_dim=1)
    assert s.spec[1] == "dp"
    plain = compiler.CompiledProgram(main)
    assert plain.feed_sharding(np.zeros((8, 3))) is None


def test_sharded_window_prefetch_places_shards():
    """Under a 2-device mesh, the background window prefetch stages
    stacked [k, B, ...] feeds pre-sharded over 'dp' on the batch axis
    (axis 1) — and the batched run consumes them bit-identically to the
    single-device trajectory."""
    import jax

    from paddle_tpu.fluid import compiler

    B, D = 8, 3
    main, startup, reader, loss = _pyreader_program(B, D)
    cp = compiler.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=jax.devices()[:2])
    reader.decorate_tensor_provider(lambda: iter(_batches(6, B, D)))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        reader.start()
        exe.run(cp, fetch_list=[loss], iters=2, prefetch=True,
                fetch_mode="async")
        # inspect the in-flight prefetch of window 2 before consuming it
        (pf,) = exe._window_prefetch.values()
        pf._thread.join()
        status, feed = pf._result
        assert status == "ok"
        for v in feed.values():
            assert isinstance(v, jax.Array)
            assert len(v.sharding.device_set) == 2
            assert v.sharding.spec[1] == "dp"
        (h,) = exe.run(cp, fetch_list=[loss], iters=2, prefetch=True,
                       fetch_mode="async")
        assert np.isfinite(h.numpy()).all()
    exe.close()


def test_loader_sharding_aware_staging():
    """GeneratorLoader(sharding=CompiledProgram) stages every batch with
    the program's feed sharding — 2 devices hold the shards before the
    executor ever sees the feed."""
    import jax

    from paddle_tpu.fluid import compiler
    from paddle_tpu.fluid.reader import DataLoader

    main, startup, loss = _sgd_program()
    cp = compiler.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=jax.devices()[:2])
    x = [v for v in main.list_vars() if v.name == "x"][0]
    loader = DataLoader.from_generator(feed_list=[x], capacity=2,
                                       sharding=cp)

    def gen():
        for i in range(3):
            yield [np.full((8, 4), i, np.float32)]

    loader.set_batch_generator(gen)
    feeds = list(loader)
    assert len(feeds) == 3
    for f in feeds:
        a = f["x"]
        assert isinstance(a, jax.Array)
        assert len(a.sharding.device_set) == 2


def test_use_double_buffer_false_disables_staging_and_thread():
    """use_double_buffer=False is a real switch now: no prefetch thread
    is spawned and feeds stay host-side numpy (staged at dispatch), not
    pre-put jax Arrays."""
    import jax

    from paddle_tpu.fluid.reader import DataLoader

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("dbx", shape=[4], dtype="float32")
    loader = DataLoader.from_generator(feed_list=[x], capacity=4,
                                       use_double_buffer=False)

    seen_threads = []

    def gen():
        for i in range(3):
            seen_threads.append(threading.current_thread())
            yield [np.full((2, 4), i, np.float32)]

    loader.set_batch_generator(gen)
    feeds = list(loader)
    assert len(feeds) == 3
    assert all(t is threading.main_thread() for t in seen_threads), \
        "use_double_buffer=False must not run the generator on a thread"
    for f in feeds:
        assert isinstance(f["dbx"], np.ndarray)
        assert not isinstance(f["dbx"], jax.Array)


def test_device_stager_error_propagates_and_joins():
    def gen():
        yield {"a": np.zeros(2, np.float32)}
        raise RuntimeError("boom in producer")

    stager = DeviceStager(gen(), capacity=2)
    assert "a" in next(stager)
    with pytest.raises(RuntimeError, match="boom in producer"):
        next(stager)
    assert not stager._thread.is_alive()


def test_stage_feed_passthrough_and_put():
    import jax

    out = stage_feed({"a": np.ones((2, 2), np.float32), "b": "raw"})
    assert isinstance(out["a"], jax.Array)
    assert out["b"] == "raw"


# -- acceptance: no host sync between windows --------------------------------

def test_async_prefetch_overlaps_windows():
    """The acceptance criterion: with fetch_mode="async" + prefetch, N
    back-to-back iters=k windows run in less wall-clock than N x
    (window compute + per-window feed work), because window i+1's feed
    work (reader sleep, calibrated to the window's own compute time)
    happens WHILE window i computes — and the executor records zero
    fetch syncs until .numpy()."""
    import jax

    n, m, k, N = 256, 10, 2, 5
    B = 256
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = layers.py_reader(capacity=8, shapes=[[B, n]],
                                  dtypes=["float32"])
        x = layers.read_file(reader)
        w = layers.create_parameter([n, n], "float32", name="ov_w")
        h = x
        for _ in range(m):
            h = layers.matmul(h, w)
            h = h * 0.01  # keep magnitudes bounded over the chain
        loss = layers.reduce_mean(h)
        optimizer.SGD(learning_rate=1e-4).minimize(loss)

    delay = {"s": 0.0}  # set after calibration; the generator reads it
    data = np.random.RandomState(0).rand(B, n).astype(np.float32)

    def gen():
        while True:
            time.sleep(delay["s"])
            yield (data,)

    reader.decorate_tensor_provider(gen)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        reader.start()
        # untimed compile window, then calibrate the window compute time
        exe.run(main, fetch_list=[loss], iters=k)
        t0 = time.perf_counter()
        (h,) = exe.run(main, fetch_list=[loss], iters=k,
                       fetch_mode="async")
        h.block_until_ready()
        t_c = time.perf_counter() - t0
        if t_c < 0.02:
            pytest.skip("window compute too fast to measure overlap "
                        "reliably on this host (%.4fs)" % t_c)

        # per-window feed work == window compute: a serial loop costs
        # ~2*t_c per window, an overlapped one ~t_c
        delay["s"] = t_c / k
        reader.reset()
        reader.start()
        monitor.reset()
        handles = []
        t0 = time.perf_counter()
        for _ in range(N):
            (h,) = exe.run(main, fetch_list=[loss], iters=k,
                           fetch_mode="async", prefetch=True)
            handles.append(h)
        handles[-1].block_until_ready()
        wall = time.perf_counter() - t0

        hist = monitor.get_metric("executor_fetch_sync_seconds")
        assert hist.count == 0, (
            "async windows must not sync before .numpy() (%d syncs)"
            % hist.count)
        assert monitor.counter(
            "executor_window_overlap_hit_total").value >= N - 1
        for h in handles:
            assert np.isfinite(h.numpy()).all()
        assert hist.count == len(handles)

        serial_estimate = N * (t_c + k * delay["s"])
        assert wall < 0.9 * serial_estimate, (
            "no overlap: N=%d windows took %.3fs, serial estimate %.3fs "
            "(window compute %.3fs, feed work %.3fs/window)"
            % (N, wall, serial_estimate, t_c, k * delay["s"]))
    exe.close()
