"""Subprocess runner for the exchange-based global shuffle test: trainer
k loads ONLY its own file, runs the network exchange, and writes the
keys of the samples it ended up with."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import layers  # noqa: E402
from paddle_tpu.distributed.sample_exchange import ExchangeServer  # noqa: E402


def main():
    cfg = json.loads(sys.argv[1])
    tid = cfg["trainer_id"]
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        dense = layers.data("dense", [3])
        ids = layers.data("ids", [1], dtype="int64")
        label = layers.data("label", [1])
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(2)
    ds.set_use_var([dense, ids, label])
    ds.set_filelist([cfg["files"][tid]])   # ONLY this trainer's shard
    ds.load_into_memory()
    n_loaded = ds.get_memory_data_size()

    # rendezvous: bind port 0 ourselves (no parent-side TOCTOU), publish
    # it, and wait for every peer's published port
    import time

    server = ExchangeServer(port=0, token="xchg")
    with open(cfg["rdv"][tid] + ".tmp", "w") as f:
        f.write(str(server.port))
    os.replace(cfg["rdv"][tid] + ".tmp", cfg["rdv"][tid])
    ports = []
    deadline = time.time() + 120
    for path in cfg["rdv"]:
        while not os.path.exists(path):
            if time.time() > deadline:
                raise TimeoutError("peer rendezvous file missing: " + path)
            time.sleep(0.1)
        ports.append(int(open(path).read()))
    endpoints = ["127.0.0.1:%d" % p for p in ports]
    ds.set_exchange(server, endpoints, seed=100 + tid)
    ds.global_shuffle()
    keys = ["%.6f" % float(s[0][0]) for s in ds._samples]
    # back-to-back second round: peers proceed at whatever skew they
    # have — the round id in the exchange frames keeps rounds apart
    ds.global_shuffle()
    keys2 = ["%.6f" % float(s[0][0]) for s in ds._samples]
    server.stop()

    with open(cfg["out"][tid], "w") as f:
        json.dump({"loaded": n_loaded, "keys": keys, "keys2": keys2}, f)


if __name__ == "__main__":
    main()
