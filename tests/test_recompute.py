"""Recompute (activation checkpointing) — reference ``optimizer.py:3341``
``RecomputeOptimizer`` / ``backward.py:576``. The autodiff lowering must
(a) produce identical gradients with and without checkpoints and (b)
actually rematerialize: the compiled HLO re-executes forward matmuls in
the backward pass (jax.checkpoint's optimization barriers keep XLA from
CSE-ing them away)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer


def _build(use_recompute):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[32], dtype="float32")
        h1 = layers.fc(x, 64, act="tanh")
        h2 = layers.fc(h1, 64, act="tanh")
        h3 = layers.fc(h2, 64, act="tanh")
        loss = layers.mean(layers.fc(h3, 1))
        opt = optimizer.SGD(learning_rate=0.1)
        if use_recompute:
            opt = optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints([h1, h2])
        opt.minimize(loss)
    return main, startup, loss


def _train(use_recompute, steps=4):
    main, startup, loss = _build(use_recompute)
    exe = fluid.Executor()
    rng = np.random.RandomState(3)
    feed = {"x": rng.rand(8, 32).astype(np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        return [float(np.asarray(exe.run(main, feed=feed,
                                         fetch_list=[loss])[0]).ravel()[0])
                for _ in range(steps)]


def test_recompute_matches_baseline():
    base = _train(False)
    remat = _train(True)
    np.testing.assert_allclose(base, remat, rtol=1e-5)


def test_recompute_actually_rematerializes():
    import jax

    def lowered(use_recompute):
        main, startup, loss = _build(use_recompute)
        exe = fluid.Executor()
        feed = {"x": np.zeros((8, 32), np.float32)}
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            fn, args = exe.as_function(main, feed, [loss])
        return jax.jit(fn).lower(*args).as_text()

    base, remat = lowered(False), lowered(True)
    # jax.checkpoint emits optimization_barrier (so XLA can't CSE the
    # recompute away) and duplicates the checkpointed segments' matmuls
    assert remat.count("optimization_barrier") > 0
    assert remat.count("dot_general") > base.count("dot_general"), (
        "checkpointed program lowered to no extra matmuls: "
        "jax.checkpoint segments were not applied")
