"""Serving fleet: SLO-aware router over coordinated replicas with warm
respawn — membership via coordination-KV leases, balance via published
load gauges, no-loss kill-one-replica re-dispatch, typed shed."""

import json
import os
import signal
import sys
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import inference
from paddle_tpu.fluid import layers, monitor
from paddle_tpu.distributed import wire as dwire
from paddle_tpu.distributed.coordination import CoordClient, CoordServer
from paddle_tpu.serving import FleetClient, Replica, Router
from paddle_tpu.serving import protocol as fp

pytestmark = pytest.mark.fleet


class _DirectReplicaConn(dwire.Conn):
    """Test-only: talk to a replica endpoint without a router."""

    MAGIC = fp.MAGIC_REPLICA
    TOKEN_ENV = fp.ENV_TOKEN
    RETRIES = 0


# -- membership primitive (no accelerator needed) ---------------------------


def test_live_members_sweeps_expired_leases():
    """Registration = put(key, blob) + lease(key): live_members returns
    the key while the lease lives, and ONE server-side sweep evicts an
    expired member — lease AND registration blob — before the caller
    can observe it. Re-registering brings it straight back."""
    srv = CoordServer().start()
    cli = CoordClient("%s:%d" % (srv.host, srv.port))
    try:
        key = "fleet/replicas/rx"
        cli.put(key, b"{}")
        cli.lease(key, ttl=0.5)
        # a KV entry WITHOUT a lease is not a member (half-registered)
        cli.put("fleet/replicas/ghost", b"{}")
        assert cli.live_members("fleet/replicas/") == [key]
        time.sleep(0.8)
        # expiry: the sweep removes the lease and the registration blob
        assert cli.live_members("fleet/replicas/") == []
        assert cli.get(key) is None
        # ...but only under the asked-for prefix (scoped sweep)
        cli.put("other/replicas/ry", b"{}")
        cli.lease("other/replicas/ry", ttl=0.5)
        assert cli.live_members("fleet/replicas/") == []
        assert cli.live_members("other/replicas/") == ["other/replicas/ry"]
        # re-register after eviction: the same id joins again
        cli.put(key, b"{}")
        cli.lease(key, ttl=30.0)
        assert cli.live_members("fleet/replicas/") == [key]
    finally:
        cli.close()
        srv.stop()


# -- in-process fleets ------------------------------------------------------


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("fleet_model")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        prob = layers.softmax(layers.fc(h, size=3))
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(str(d), ["x"], [prob], exe,
                                      main_program=main)
    return str(d)


def _spec(model_dir, model="fc", delay_ms=2.0):
    return {"prefix": "fleet/",
            "models": [{"name": model, "model_dir": model_dir,
                        "warmup": {"x": {"shape": [1, 6],
                                         "dtype": "float32"}},
                        "config": {"max_batch_size": 8,
                                   "max_queue_delay_ms": delay_ms}}]}


class _Fleet:
    """CoordServer + N in-process replicas + router + client, torn down
    in reverse order."""

    def __init__(self, model_dir, n, model="fc", rid_prefix="rep",
                 lease_ttl=1.0):
        self.coord = CoordServer().start()
        self.addr = "%s:%d" % (self.coord.host, self.coord.port)
        spec = _spec(model_dir, model=model)
        self.replicas = [
            Replica(spec, coord_addr=self.addr,
                    replica_id="%s%d" % (rid_prefix, i),
                    lease_ttl=lease_ttl, stats_interval=0.05).start()
            for i in range(n)]
        self.router = Router(coord_addr=self.addr,
                             refresh_interval=0.05).start()
        self.client = FleetClient(
            "%s:%d" % (self.router.host, self.router.port))

    def close(self):
        self.client.close()
        self.router.close()
        for r in self.replicas:
            r.drain(timeout=5)
        self.coord.stop()


def test_fleet_round_trip_and_balance(model_dir):
    """Requests through router + replicas match the direct predictor,
    and equal-load replicas share the traffic (both routed counters
    advance — the occupancy/balance acceptance gauge)."""
    f = _Fleet(model_dir, 2, model="bal", rid_prefix="bal")
    try:
        assert sorted(f.router.members()) == ["bal0", "bal1"]
        direct = inference.create_predictor(inference.Config(model_dir))
        rng = np.random.RandomState(3)
        for _ in range(16):
            x = rng.rand(rng.randint(1, 5), 6).astype(np.float32)
            out = f.client.submit("bal", {"x": x}, deadline_ms=10000)
            np.testing.assert_allclose(out[0], direct.run({"x": x})[0],
                                       atol=1e-5)
        per = {rid: monitor.counter("fleet_replica_routed_total",
                                    labels={"replica": rid}).value
               for rid in ("bal0", "bal1")}
        assert sum(per.values()) == 16
        assert per["bal0"] > 0 and per["bal1"] > 0, per
        assert monitor.get_metric("fleet_routed_total",
                                  labels={"model": "bal"}).value == 16
        e2e = monitor.get_metric("fleet_request_seconds",
                                 labels={"model": "bal"})
        assert e2e.count == 16 and 0 < e2e.quantile(0.5) <= e2e.quantile(0.99)
    finally:
        f.close()


def test_kill_one_replica_loses_no_requests(model_dir):
    """A killed replica (wire severed, lease left to expire — the crash
    shape) costs ZERO requests: in-flight forwards fail, the router
    evicts eagerly, re-dispatches (fleet_requeued_total), and lease
    expiry removes the corpse from the membership view."""
    f = _Fleet(model_dir, 2, model="kill", rid_prefix="kil",
               lease_ttl=0.6)
    try:
        direct = inference.create_predictor(inference.Config(model_dir))
        rng = np.random.RandomState(5)
        # warm traffic so the router's conn pool reaches BOTH replicas
        for _ in range(8):
            x = rng.rand(2, 6).astype(np.float32)
            f.client.submit("kill", {"x": x}, deadline_ms=10000)
        requeued0 = monitor.counter("fleet_requeued_total").value
        f.replicas[0].kill()
        for _ in range(10):
            x = rng.rand(2, 6).astype(np.float32)
            out = f.client.submit("kill", {"x": x}, deadline_ms=10000)
            np.testing.assert_allclose(out[0], direct.run({"x": x})[0],
                                       atol=1e-5)
        assert monitor.counter("fleet_requeued_total").value > requeued0
        # the lease is the authority: the corpse leaves the coord view,
        # then the router's
        dbg = CoordClient(f.addr)
        deadline = time.time() + 10
        while ("fleet/replicas/kil0" in dbg.live_members("fleet/replicas/")
               and time.time() < deadline):
            time.sleep(0.05)
        assert dbg.live_members("fleet/replicas/") == \
            ["fleet/replicas/kil1"]
        dbg.close()
        while "kil0" in f.router.members() and time.time() < deadline:
            time.sleep(0.05)
        assert sorted(f.router.members()) == ["kil1"]
    finally:
        f.close()


def test_drain_deregisters_and_redirects(model_dir):
    """Graceful drain: the replica deregisters (KV deleted — it leaves
    the membership view without waiting out the lease), later traffic
    lands on the survivor, and double-drain is a no-op."""
    f = _Fleet(model_dir, 2, model="drn", rid_prefix="drn")
    try:
        rng = np.random.RandomState(7)
        f.replicas[0].drain(timeout=10)
        f.replicas[0].drain(timeout=10)   # idempotent
        dbg = CoordClient(f.addr)
        assert dbg.live_members("fleet/replicas/") == \
            ["fleet/replicas/drn1"]
        dbg.close()
        for _ in range(4):
            x = rng.rand(1, 6).astype(np.float32)
            out = f.client.submit("drn", {"x": x}, deadline_ms=10000)
            assert out[0].shape == (1, 3)
        assert monitor.counter("fleet_replica_routed_total",
                               labels={"replica": "drn1"}).value >= 4
    finally:
        f.close()


def test_empty_fleet_sheds_typed(model_dir):
    """No live replica: the router answers ST_OVERLOADED and the client
    raises the typed Overloaded — never a hang, never a bare error."""
    coord = CoordServer().start()
    router = Router(coord_addr="%s:%d" % (coord.host, coord.port),
                    refresh_interval=0.05).start()
    cli = FleetClient("%s:%d" % (router.host, router.port))
    try:
        shed0 = monitor.sum_labeled("fleet_shed_total")
        with pytest.raises(inference.Overloaded, match="no live replica"):
            cli.submit("fc", {"x": np.zeros((1, 6), np.float32)},
                       deadline_ms=500)
        assert monitor.sum_labeled("fleet_shed_total") == shed0 + 1
    finally:
        cli.close()
        router.close()
        coord.stop()


def test_draining_replica_answers_typed_closed(model_dir):
    """ST_CLOSED crosses the wire as the typed ``Closed``: a draining
    replica tells a DIRECT client (no router in between to re-pick)
    that retrying against it can never succeed."""
    r = Replica(_spec(model_dir, model="cls"), replica_id="cls0").start()
    try:
        r._draining = True        # drain flag only; wire stays up
        conn = _DirectReplicaConn(r.endpoint)
        try:
            req = fp.pack_request(
                fp.OP_INFER, "cls",
                {"x": np.zeros((1, 6), np.float32)}, 1000.0, 0)
            with pytest.raises(inference.Closed, match="draining"):
                fp.raise_for_status(conn.request(req))
        finally:
            conn.close()
    finally:
        r._draining = False
        r.drain(timeout=5)


# -- subprocess fleet (supervisor, SIGTERM drain, warm respawn) -------------


@pytest.mark.slow
def test_supervisor_sigterm_drain_and_warm_respawn(model_dir, tmp_path):
    """The full process story: FleetSupervisor spawns replica processes,
    SIGTERM drains one gracefully (exit 0 through the preemption path),
    and the respawned process re-registers under the SAME id on a fresh
    endpoint. With prelowered models + a shared compile cache the
    respawn reports zero live compiles before rejoining."""
    from paddle_tpu.serving.supervisor import FleetSupervisor

    # prelower the served ladder: children then load executables from
    # <model>/__prelowered__ instead of tracing+compiling live
    pre_dir = str(tmp_path / "pre_model")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        prob = layers.softmax(layers.fc(h, size=3))
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(
            pre_dir, ["x"], [prob], exe, main_program=main,
            prelower=True, prelower_batch_sizes=(1, 2, 4, 8))
    env = {"PADDLE_FLEET_LEASE_TTL": "2.0"}
    coord = CoordServer().start()
    addr = "%s:%d" % (coord.host, coord.port)
    sup = FleetSupervisor(_spec(pre_dir), 1, addr, env=env,
                          log_dir=str(tmp_path))
    dbg = CoordClient(addr)
    try:
        sup.start()
        deadline = time.time() + 180
        key = "fleet/replicas/rep0"
        while (key not in dbg.live_members("fleet/replicas/")
               and time.time() < deadline):
            time.sleep(0.2)
        blob = json.loads(dbg.get(key).decode())
        pid0 = blob["pid"]
        assert blob["models"] == ["fc"]
        # SIGTERM-drain with respawn: preemption machinery finishes
        # in-flight work, deregisters, exits 0; the supervisor brings a
        # fresh process up under the same id
        rc = sup.drain("rep0", respawn=True, timeout=60)
        assert rc == 0
        while time.time() < deadline:
            blob = dbg.get(key)
            if blob is not None:
                info = json.loads(blob.decode())
                if info["pid"] != pid0:
                    break
            time.sleep(0.2)
        info = json.loads(dbg.get(key).decode())
        assert info["pid"] != pid0 and sup.respawns >= 1
        # warm respawn: zero live compiles — every ladder executable
        # came off __prelowered__ disk entries
        assert info["live_compiles"] == 0, info
        assert info["warmup_disk_hits"] > 0, info
    finally:
        dbg.close()
        sup.stop(timeout=30)
        coord.stop()
