"""IR construction, cloning, pruning, protobuf round-trip.

Mirrors reference tests: test_program.py, test_operator_desc.py,
test_protobuf_descs.py (SURVEY §4)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, layers


def _build_program():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3, act="relu")
        loss = layers.mean(y)
    return main, startup, loss


def test_program_construction():
    main, startup, loss = _build_program()
    types = [op.type for op in main.global_block().ops]
    assert "mul" in types
    assert "relu" in types
    assert "mean" in types
    params = main.all_parameters()
    assert len(params) == 2  # weight + bias
    # startup has init ops for both params
    assert len(startup.global_block().ops) >= 2


def test_shape_inference():
    main, _, loss = _build_program()
    # fc output inferred as (-1, 3)
    fc_out = None
    for op in main.global_block().ops:
        if op.type == "relu":
            fc_out = main.global_block().var(op.output("Out")[0])
    assert fc_out is not None
    assert fc_out.shape == (-1, 3)
    assert loss.shape == ()


def test_clone_for_test_flips_is_test():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data(name="x", shape=[4])
        d = layers.dropout(x, dropout_prob=0.5)
    cloned = main.clone(for_test=True)
    ops = [op for op in cloned.global_block().ops if op.type == "dropout"]
    assert ops[0].attr("is_test") is True
    # original untouched
    ops0 = [op for op in main.global_block().ops if op.type == "dropout"]
    assert ops0[0].attr("is_test") is False


def test_prune():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data(name="x", shape=[4])
        h = layers.fc(x, size=3)
        out1 = layers.mean(h)
        out2 = layers.reduce_sum(h)  # should be pruned away
    pruned = main._prune([out1])
    types = [op.type for op in pruned.global_block().ops]
    assert "reduce_sum" not in types
    assert "mean" in types


def test_prune_keeps_subblock_dependencies(tmp_path):
    """Multi-block prune (reference prune.h): a cond branch reads a
    block-0 fc output that is NOT an explicit input of the cond op, and
    a While body WRITES the served var without the while op declaring
    outputs. Pruning must keep both chains; the saved model must reload
    and serve the same values (VERDICT r4 #6)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        label = layers.data("label", shape=[1])
        h = layers.fc(x, size=3, act="relu")       # read ONLY inside cond
        pred = layers.reduce_mean(x) > 0.0
        # branch closures capture h — the cond op's explicit inputs list
        # only the predicate
        branched = layers.cond(pred,
                               lambda: h * 2.0,
                               lambda: h + 1.0)
        # While body mutates `acc` in the parent block; the while op
        # declares no outputs at all
        acc = layers.fill_constant([1], "float32", 0.0)
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 3)
        w_cond = layers.less_than(i, n)
        w = layers.While(w_cond)
        with w.block():
            layers.assign(acc + 1.0, acc)
            layers.increment(i)
            layers.less_than(i, n, cond=w_cond)
        # Switch stores its branch blocks as attrs["blocks"] (a LIST)
        # and declares no outputs — the LR-scheduling idiom
        lr = layers.fill_constant([1], "float32", 0.0)
        with layers.Switch() as sw:
            with sw.case(layers.reduce_mean(x) > -1000.0):  # always true
                layers.assign(layers.fill_constant([1], "float32", 10.0),
                              lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 20.0),
                              lr)
        out = branched + acc + lr                  # serve this
        loss = layers.reduce_mean(
            layers.square_error_cost(layers.reduce_sum(out, keep_dim=True),
                                     label))
        optimizer.SGD(learning_rate=0.01).minimize(loss)

    exe = fluid.Executor()
    rng = np.random.RandomState(3)
    feed = {"x": rng.rand(2, 4).astype(np.float32),
            "label": rng.rand(2, 1).astype(np.float32)}
    model_dir = str(tmp_path / "cf_model")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe,
                                      main_program=main)
        (expect,) = exe.run(main, feed=feed, fetch_list=[out])
    # the pruned program kept the hidden fc AND the while chain, and
    # dropped the training tail
    pruned = main._prune([out])
    types = [op.type for op in pruned.global_block().ops]
    assert "cond" in types and "while" in types and "switch" in types
    assert "mul" in types and "relu" in types           # h's fc survives
    assert "sgd" not in types and "square_error_cost" not in types
    # reload in a fresh scope and serve: identical values
    with fluid.scope_guard(fluid.Scope()):
        prog, feeds, fetches = fluid.io.load_inference_model(model_dir, exe)
        (got,) = exe.run(prog, feed={"x": feed["x"]}, fetch_list=fetches)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5)


def test_save_inference_model_keeps_train_mode_when_not_deploying(tmp_path):
    """export_for_deployment=False saves the program AS BUILT: a
    reloaded program keeps dropout/batch-norm in training mode (no
    clone(for_test=True) flip) so it can resume training."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[100], dtype="float32")
        d = layers.dropout(x, dropout_prob=0.5,
                           dropout_implementation="upscale_in_train")
        out = layers.reduce_sum(d, keep_dim=True)
    exe = fluid.Executor()
    model_dir = str(tmp_path / "train_mode_model")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe,
                                      main_program=main,
                                      export_for_deployment=False)
        prog, feeds, fetches = fluid.io.load_inference_model(model_dir, exe)
    drops = [op for op in prog.global_block().ops if op.type == "dropout"]
    assert drops and drops[0].attrs.get("is_test") is False
    # and it behaves like training mode: some activations are zeroed
    xv = np.ones((2, 100), np.float32)
    with fluid.scope_guard(fluid.Scope()):
        (got,) = exe.run(prog, feed={"x": xv},
                         fetch_list=[d.name])
    assert (np.asarray(got) == 0).any()
    # the deployment export of the same program IS eval-mode
    deploy_dir = str(tmp_path / "deploy_model")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(deploy_dir, ["x"], [out], exe,
                                      main_program=main)
        prog2, _, _ = fluid.io.load_inference_model(deploy_dir, exe)
    drops2 = [op for op in prog2.global_block().ops
              if op.type == "dropout"]
    assert drops2 and drops2[0].attrs.get("is_test") is True


def test_protobuf_roundtrip():
    main, _, loss = _build_program()
    data = main.serialize_to_string()
    assert isinstance(data, bytes) and len(data) > 0
    restored = fluid.Program.parse_from_string(data)
    assert [op.type for op in restored.global_block().ops] == [
        op.type for op in main.global_block().ops
    ]
    # var metadata survives
    for name, var in main.global_block().vars.items():
        rvar = restored.global_block().var(name)
        assert tuple(rvar.shape) == tuple(var.shape)
        assert rvar.persistable == var.persistable
    # parameters survive as parameters
    assert {p.name for p in restored.all_parameters()} == {
        p.name for p in main.all_parameters()
    }


def test_operator_sugar():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data(name="x", shape=[4])
        y = layers.data(name="y", shape=[4])
        z = x + y
        w = z * 2.0
        c = x < y
    assert z.dtype == np.dtype("float32")
    assert c.dtype == np.dtype("bool")


def test_program_version_gating_and_op_compat():
    """Load-time compat checks (reference framework/version.h +
    op_compatible_info.cc): newer-writer programs and unknown op types
    fail loudly at load, not mid-execution."""
    from paddle_tpu.fluid import compat
    from paddle_tpu.fluid.core import proto_io
    from paddle_tpu.fluid.core import framework_pb2 as pb

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("vx", [4], dtype="float32")
        layers.relu(x)
    data = proto_io.program_to_bytes(main.to_desc())
    # round trip under the current version is clean
    desc = proto_io.program_from_bytes(data)
    assert desc["version"] == compat.PROGRAM_VERSION

    # a NEWER writer version must be refused at the parse boundary
    p = pb.ProgramDesc()
    p.ParseFromString(data)
    p.version = compat.PROGRAM_VERSION + 1
    with pytest.raises(proto_io.ProgramVersionError, match="version"):
        proto_io.program_from_bytes(p.SerializeToString())
    assert not compat.is_program_version_supported(
        compat.PROGRAM_VERSION + 1)

    # an unknown op type is named in the load error, distinguishable
    # from version failures by type/status
    p.version = compat.PROGRAM_VERSION
    p.blocks[0].ops.add().type = "made_up_future_op"
    with pytest.raises(proto_io.ProgramCompatError,
                       match="made_up_future_op") as ei:
        proto_io.program_from_bytes(p.SerializeToString())
    assert ei.value.status == compat.CompatibleInfo.UNDEFINED_OP
    assert not isinstance(ei.value, proto_io.ProgramVersionError)
    # ...tooling can still inspect it with the gate off
    desc2 = proto_io.program_from_bytes(p.SerializeToString(),
                                        check=False)
    assert not compat.check_program_compatible(desc2)

    # structural ops (run specially by the executor) stay loadable:
    # a pserver program round-trips
    sp = fluid.Program()
    sp.global_block().append_op("listen_and_serv", inputs={}, outputs={},
                                attrs={"endpoint": "x"})
    rt = proto_io.program_from_bytes(proto_io.program_to_bytes(
        sp.to_desc()))
    assert rt["blocks"][0]["ops"][0]["type"] == "listen_and_serv"
