"""Persistent compile cache: AOT-serialized executables on disk
(fluid/compile_cache.py) — restart hits, corruption quarantine, version
mismatch, cross-process races, prelowered models, LRU eviction."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import inference
from paddle_tpu.fluid import compile_cache, layers, monitor, unique_name

pytestmark = pytest.mark.compile_cache


def _build_regression():
    """The canonical tiny train program; unique_name.guard makes repeat
    builds byte-identical (like a fresh process would be)."""
    main, startup = fluid.Program(), fluid.Program()
    with unique_name.guard(), fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, 1, name="cc_fc")
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _feed(batch=8, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(batch, 4).astype(np.float32),
            "y": rng.rand(batch, 1).astype(np.float32)}


def _run_restart(feed, steps=2):
    """One simulated process lifetime: fresh Executor (empty memory
    tier), fresh program build, `steps` training steps."""
    main, startup, loss = _build_regression()
    exe = fluid.Executor()
    out = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            out.append(float(np.asarray(lv)))
    return out


def _counters():
    return (monitor.counter("executor_compile_cache_disk_hit_total").value,
            monitor.counter("executor_compile_cache_disk_miss_total").value,
            monitor.counter("compile_cache_quarantined_total").value)


def _entries(d):
    return sorted(f for f in os.listdir(d)
                  if f.endswith(compile_cache.ENTRY_SUFFIX))


def test_disabled_is_inert(tmp_path, monkeypatch):
    monkeypatch.delenv(compile_cache.ENV_DIR, raising=False)
    h0, m0, _ = _counters()
    losses = _run_restart(_feed())
    assert np.isfinite(losses).all()
    h1, m1, _ = _counters()
    assert (h1, m1) == (h0, m0), "disk tier consulted while disabled"


def test_restart_hits_disk_and_is_bit_identical(tmp_path, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path))
    h0, m0, _ = _counters()
    cold = _run_restart(_feed())
    h1, m1, _ = _counters()
    assert m1 - m0 == 2, "cold run: startup + main should both miss disk"
    assert h1 == h0
    assert len(_entries(str(tmp_path))) == 2
    # "restart": fresh Executor + rebuilt program, same cache dir
    warm = _run_restart(_feed())
    h2, m2, _ = _counters()
    assert warm == cold, "deserialized executable diverged from live"
    assert h2 - h1 == 2 and m2 == m1, \
        "warm restart should compile zero programs live"
    # tier-labeled view moved with the unlabeled counters
    disk_hits = monitor.counter("executor_compile_cache_hit_total",
                                labels={"tier": "disk"}).value
    assert disk_hits >= 2


def test_corrupted_entry_quarantined_never_fatal(tmp_path, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path))
    cold = _run_restart(_feed())
    paths = _entries(str(tmp_path))
    # truncate one entry, garbage-overwrite the other
    with open(os.path.join(str(tmp_path), paths[0]), "r+b") as f:
        f.truncate(17)
    with open(os.path.join(str(tmp_path), paths[1]), "wb") as f:
        f.write(b"\x80\x04 not a cache entry")
    _, m0, q0 = _counters()
    warm = _run_restart(_feed())
    _, m1, q1 = _counters()
    assert warm == cold, "fallback live compile diverged"
    assert q1 - q0 == 2, "both bad entries should be quarantined"
    assert m1 - m0 == 2, "bad entries must count as disk misses"
    # quarantined aside (evidence kept), fresh entries re-saved
    quarantined = [f for f in os.listdir(str(tmp_path))
                   if f.endswith(compile_cache.QUARANTINE_SUFFIX)]
    assert len(quarantined) == 2
    assert len(_entries(str(tmp_path))) == 2


def test_version_bump_misses_cleanly(tmp_path, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path))
    _run_restart(_feed())
    before = _entries(str(tmp_path))
    # a jax/jaxlib upgrade changes the env fingerprint -> different key
    monkeypatch.setattr(compile_cache, "FORMAT_VERSION",
                        compile_cache.FORMAT_VERSION + 1)
    h0, m0, q0 = _counters()
    _run_restart(_feed())
    h1, m1, q1 = _counters()
    assert h1 == h0, "stale-version entry must not load"
    assert m1 - m0 == 2
    assert q1 == q0, "a clean version miss is not a quarantine"
    after = _entries(str(tmp_path))
    assert set(before) < set(after) and len(after) == 4


def test_two_processes_race_same_dir(tmp_path):
    """Two fresh processes populating one cache dir concurrently: both
    succeed (atomic rename, no torn reads) and the dir converges."""
    script = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PADDLE_COMPILE_CACHE_DIR"] = sys.argv[1]
sys.path.insert(0, sys.argv[2])
import numpy as np
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, 1, name="cc_fc")
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor()
rng = np.random.RandomState(0)
feed = {"x": rng.rand(8, 4).astype(np.float32),
        "y": rng.rand(8, 1).astype(np.float32)}
with fluid.scope_guard(fluid.Scope()):
    exe.run(startup)
    (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
print("LOSS=%.9f" % float(np.asarray(lv)))
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k != compile_cache.ENV_DIR}
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, str(tmp_path), repo],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        text=True) for _ in range(2)]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err
    losses = {o.strip() for o, _ in outs}
    assert len(losses) == 1, "racing processes diverged: %r" % losses
    assert len(_entries(str(tmp_path))) == 2


def test_prelowered_model_cold_start(tmp_path, monkeypatch):
    """save_inference_model(prelower=True) -> a Predictor in a process
    with NO cache dir configured cold-starts from the model-adjacent
    executables, compiling zero programs live."""
    monkeypatch.delenv(compile_cache.ENV_DIR, raising=False)
    model_dir = str(tmp_path / "model")
    main, startup = fluid.Program(), fluid.Program()
    with unique_name.guard(), fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        pred = layers.fc(x, 3, name="pl_fc", act="softmax")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(
            model_dir, ["x"], [pred], exe, main_program=main,
            prelower=True, prelower_batch_sizes=(1, 4))
    pl_dir = os.path.join(model_dir, compile_cache.PRELOWERED_DIRNAME)
    assert len(_entries(pl_dir)) == 2
    h0, m0, _ = _counters()
    p = inference.Predictor(inference.Config(model_dir=model_dir))
    out4 = p.run({"x": np.ones((4, 4), np.float32)})
    h1, m1, _ = _counters()
    assert h1 - h0 == 1 and m1 == m0, "prelowered batch=4 should hit"
    assert np.allclose(np.sum(out4[0], axis=1), 1.0, atol=1e-5)
    # a batch size outside the prelowered set compiles live, and with
    # no write dir configured it must NOT write into the model dir
    p.run({"x": np.ones((2, 4), np.float32)})
    h2, m2, _ = _counters()
    assert h2 == h1 and m2 - m1 == 1
    assert len(_entries(pl_dir)) == 2


def test_cold_serve_values_match(tmp_path):
    """A COLD process serving through deserialized prelowered
    executables must return the same values as the live program.

    Regression: inference executables used to be serialized with state
    donation baked in; the deserialized copies then ran in-place over
    param buffers, so a cold Server returned stale or garbage rows
    (the in-process path hides this — only a fresh process serves
    through the deserialized executables with nothing else resolved).
    """
    model_dir = str(tmp_path / "model")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with unique_name.guard(), fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        pred = layers.fc(x, 3, name="cs_fc", act="softmax")
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(
            model_dir, ["x"], [pred], exe, main_program=main,
            prelower=True, prelower_batch_sizes=(1, 2))
    # ground truth straight from the saved params — independent of any
    # executable, live or deserialized
    w = np.asarray(scope.vars["cs_fc.w_0"])
    b = np.asarray(scope.vars["cs_fc.b_0"])
    rng = np.random.RandomState(7)
    feeds = [rng.rand(rng.randint(1, 3), 4).astype(np.float32)
             for _ in range(8)]
    np.savez(str(tmp_path / "feeds.npz"),
             **{"f%d" % i: f for i, f in enumerate(feeds)})
    script = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PADDLE_COMPILE_CACHE_DIR", None)
sys.path.insert(0, sys.argv[2])
import numpy as np
from paddle_tpu import inference
from paddle_tpu.fluid import monitor
d = np.load(os.path.join(sys.argv[1], "feeds.npz"))
feeds = [d["f%d" % i] for i in range(8)]
p = inference.Predictor(os.path.join(sys.argv[1], "model"))
srv = inference.Server()
srv.register("m", p, inference.ServeConfig(max_batch_size=2,
                                           max_queue_delay_ms=1.0),
             warmup_feed={"x": np.zeros((1, 4), np.float32)})
outs = [srv.submit("m", {"x": f}).result(timeout=60)[0] for f in feeds]
srv.close()
np.savez(os.path.join(sys.argv[1], "outs.npz"),
         **{"o%d" % i: o for i, o in enumerate(outs)})
print("MISS=%d" % monitor.counter(
    "executor_compile_cache_disk_miss_total").value)
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k != compile_cache.ENV_DIR}
    r = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path), repo],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "MISS=0" in r.stdout, \
        "cold serve compiled live instead of deserializing: %s" % r.stdout
    got = np.load(str(tmp_path / "outs.npz"))
    for i, f in enumerate(feeds):
        z = f @ w + b
        e = np.exp(z - z.max(axis=1, keepdims=True))
        ref = e / e.sum(axis=1, keepdims=True)
        np.testing.assert_allclose(
            got["o%d" % i], ref, rtol=1e-4, atol=1e-5,
            err_msg="cold-served request %d diverged from the saved "
                    "params' forward pass" % i)


def test_lru_eviction_by_mtime(tmp_path, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path))
    _run_restart(_feed())
    entries = _entries(str(tmp_path))
    assert len(entries) == 2
    sizes = {f: os.path.getsize(os.path.join(str(tmp_path), f))
             for f in entries}
    # age one entry far into the past, then set a budget that only fits
    # the other: the old one must go
    newest = max(entries, key=lambda f: os.path.getmtime(
        os.path.join(str(tmp_path), f)))
    oldest = [f for f in entries if f != newest][0]
    old_path = os.path.join(str(tmp_path), oldest)
    os.utime(old_path, (1, 1))
    monkeypatch.setenv(compile_cache.ENV_MAX_BYTES,
                       str(sizes[newest] + 16))
    e0 = monitor.counter("compile_cache_evicted_total").value
    evicted = compile_cache._evict(str(tmp_path))
    assert evicted == 1
    assert _entries(str(tmp_path)) == [newest]
    assert monitor.counter("compile_cache_evicted_total").value - e0 == 1


def test_prewarm_validates_and_quarantines(tmp_path, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path))
    _run_restart(_feed())
    bad = os.path.join(str(tmp_path), "0" * 64 + compile_cache.ENTRY_SUFFIX)
    with open(bad, "wb") as f:
        f.write(b"torn write")
    _, _, q0 = _counters()
    ok = compile_cache.prewarm(str(tmp_path))
    _, _, q1 = _counters()
    assert ok == 2
    assert q1 - q0 == 1
    assert not os.path.exists(bad)
    # the quarantined bytes are kept aside for postmortem
    assert os.path.exists(bad + compile_cache.QUARANTINE_SUFFIX)


def test_restore_on_restart_prewarms(tmp_path, monkeypatch):
    """A launcher-restarted worker (PADDLE_RESTART_ATTEMPT>0) validates
    the cache before its first step: the corrupt entry is quarantined
    by restore_on_restart itself, not discovered mid-step."""
    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path))
    bad = os.path.join(str(tmp_path), "f" * 64 + compile_cache.ENTRY_SUFFIX)
    with open(bad, "wb") as f:
        f.write(b"garbage")
    monkeypatch.setenv("PADDLE_RESTART_ATTEMPT", "1")
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path / "ckpt"))
    mgr = fluid.io.CheckpointManager(str(tmp_path / "ckpt"))
    _, _, q0 = _counters()
    assert mgr.restore_on_restart() is None  # no checkpoint yet
    _, _, q1 = _counters()
    assert q1 - q0 == 1 and not os.path.exists(bad)
