"""Control flow: cond, While, while_loop, case/switch_case, StaticRNN.

Reference analogues: test_while_op.py, test_cond.py, test_case.py,
test_recurrent_op.py."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _run(main, feed, fetch):
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        return exe.run(main, feed=feed, fetch_list=fetch)


def test_cond():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data(name="x", shape=[4], dtype="float32")
        pred = layers.reduce_sum(x) > 0.0
        out = layers.cond(pred,
                          lambda: layers.scale(x, scale=2.0),
                          lambda: layers.scale(x, scale=-1.0))
    xv = np.ones((2, 4), np.float32)
    (r,) = _run(main, {"x": xv}, [out])
    np.testing.assert_allclose(r, 2 * xv)
    (r,) = _run(main, {"x": -xv}, [out])
    np.testing.assert_allclose(r, xv)


def test_while_op_accumulate():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 10)
        acc = layers.fill_constant([1], "float32", 0.0)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            new_acc = layers.scale(acc, scale=1.0, bias=2.0)
            layers.assign(new_acc, acc)
            layers.increment(i, value=1)
            layers.less_than(i, n, cond=cond)
    (r, iv) = _run(main, {}, [acc, i])
    assert float(r) == 20.0
    assert int(iv) == 10


def test_while_loop_functional():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        i = layers.fill_constant([1], "int64", 0)
        s = layers.fill_constant([1], "float32", 1.0)

        def cond_fn(i, s):
            n = layers.fill_constant([1], "int64", 5)
            return layers.less_than(i, n)

        def body_fn(i, s):
            s2 = layers.scale(s, scale=2.0)
            i2 = layers.scale(i, scale=1.0, bias=1.0)
            return [i2, s2]

        i_out, s_out = layers.while_loop(cond_fn, body_fn, [i, s])
    (iv, sv) = _run(main, {}, [i_out, s_out])
    assert int(iv) == 5
    assert float(sv) == 32.0


def test_case_and_switch_case():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        idx = layers.data(name="idx", shape=[1], dtype="int64",
                          append_batch_size=False)
        out = layers.switch_case(
            idx,
            {0: lambda: layers.fill_constant([2], "float32", 10.0),
             1: lambda: layers.fill_constant([2], "float32", 20.0)},
            default=lambda: layers.fill_constant([2], "float32", -1.0))
    (r,) = _run(main, {"idx": np.array([1], np.int64)}, [out])
    np.testing.assert_allclose(r, [20.0, 20.0])
    (r,) = _run(main, {"idx": np.array([0], np.int64)}, [out])
    np.testing.assert_allclose(r, [10.0, 10.0])
    (r,) = _run(main, {"idx": np.array([7], np.int64)}, [out])
    np.testing.assert_allclose(r, [-1.0, -1.0])


def test_static_rnn_scan():
    T, B, D, H = 6, 2, 3, 4
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[T, B, D], dtype="float32",
                        append_batch_size=False)
        h0 = layers.data(name="h0", shape=[B, H], dtype="float32",
                         append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(init=h0)
            h = layers.fc([x_t, h_prev], size=H, act="tanh",
                          bias_attr=False)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        outs = rnn()
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    xv = rng.rand(T, B, D).astype(np.float32)
    h0v = np.zeros((B, H), np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        # fetch weights for the numpy reference
        params = main.all_parameters()
        w_names = [p.name for p in params]
        res = exe.run(main, feed={"x": xv, "h0": h0v},
                      fetch_list=[outs] + w_names)
    out, ws = res[0], res[1:]
    wx, wh = ws[0], ws[1]
    h = h0v
    for t in range(T):
        h = np.tanh(xv[t] @ wx + h @ wh)
        np.testing.assert_allclose(out[t], h, rtol=1e-4, atol=1e-5)


def test_while_backward():
    """Gradient flows through lax.while_loop via the autodiff op."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[3], dtype="float32")
        x.stop_gradient = False

        def cond_fn(i, s):
            n = layers.fill_constant([1], "int64", 3)
            return layers.less_than(i, n)

        def body_fn(i, s):
            return [layers.scale(i, scale=1.0, bias=1.0),
                    layers.elementwise_mul(s, s)]

        i0 = layers.fill_constant([1], "int64", 0)
        _, s_out = layers.while_loop(cond_fn, body_fn, [i0, x],
                                     maximum_trip_count=4)
        loss = layers.reduce_sum(s_out)
        (gx,) = fluid.gradients(loss, x)
    xv = np.full((1, 3), 1.1, np.float32)
    # s -> s^2 three times => s^8; ds/dx = 8 x^7
    (g,) = _run(main, {"x": xv}, [gx])
    np.testing.assert_allclose(g, 8 * xv ** 7, rtol=1e-4)


# -- bounded TensorArray (reference control_flow.py:1113/:1466/:1578,
#    tensor.py:279) ----------------------------------------------------------

def test_tensor_array_write_read_roundtrip():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 3], dtype="float32",
                        append_batch_size=False)
        arr = layers.create_array("float32")
        arr = layers.array_write(x, 0, arr)
        arr = layers.array_write(x * 2.0, 1, arr)
        n = layers.array_length(arr)
        r0 = layers.array_read(arr, 0)
        r1 = layers.array_read(arr, 1)
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    nv, v0, v1 = _run(main, {"x": xv}, [n, r0, r1])
    assert nv[0] == 2
    np.testing.assert_allclose(v0, xv)
    np.testing.assert_allclose(v1, 2 * xv)


def test_tensor_array_in_while_and_to_tensor():
    """array_write inside a While accumulates across iterations (the
    @ALEN length rides the loop carry); tensor_array_to_tensor stacks
    and concats the slots."""
    main, startup = fluid.Program(), fluid.Program()
    T = 4
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 3], dtype="float32",
                        append_batch_size=False)
        arr = layers.create_array("float32", element_shape=[2, 3], bound=T)
        i = layers.fill_constant([1], "int32", 0)
        limit = layers.fill_constant([1], "int32", T)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with w.block():
            fi = layers.cast(i, "float32")
            layers.array_write(x * fi, i, arr)
            layers.increment(i, 1)
            layers.less_than(i, limit, cond=cond)
        n = layers.array_length(arr)
        stacked, sidx = layers.tensor_array_to_tensor(arr, axis=0,
                                                      use_stack=True)
        cat, cidx = layers.tensor_array_to_tensor(arr, axis=1)
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    nv, sv, siv, cv, civ = _run(main, {"x": xv}, [n, stacked, sidx, cat,
                                                  cidx])
    want = np.stack([xv * t for t in range(T)])
    assert nv[0] == T
    np.testing.assert_allclose(sv, want)
    np.testing.assert_allclose(cv, np.concatenate(list(want), axis=1))
    assert list(civ) == [3] * T  # per-slot size along axis=1


def test_dynamic_rnn_matches_static_rnn_equal_lengths():
    """On equal-length input DynamicRNN's masking is inert: it must equal
    StaticRNN on the same accumulation body."""
    B, T, D = 3, 5, 4
    rng = np.random.RandomState(0)
    flat = rng.randn(B * T, D).astype(np.float32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, D], dtype="float32",
                        append_batch_size=False, lod_level=1)
        drnn = layers.DynamicRNN(maxlen=T)
        with drnn.block():
            xt = drnn.step_input(x)
            h = drnn.memory(shape=[D], value=0.0, batch_ref=xt)
            nh = layers.elementwise_add(h, xt)
            drnn.update_memory(h, nh)
            drnn.output(nh)
        out = drnn()

        xs = layers.data("xs", shape=[T, B, D], dtype="float32",
                         append_batch_size=False)
        srnn = layers.StaticRNN()
        with srnn.step():
            xt2 = srnn.step_input(xs)
            h2 = srnn.memory(shape=[B, D], value=0.0)
            nh2 = layers.elementwise_add(h2, xt2)
            srnn.update_memory(h2, nh2)
            srnn.step_output(nh2)
        sout = srnn()
    feed = {"x": fluid.create_lod_tensor(flat, [[T] * B]),
            "xs": flat.reshape(B, T, D).transpose(1, 0, 2)}
    dv, sv = _run(main, feed, [out, sout])
    np.testing.assert_allclose(dv, sv.transpose(1, 0, 2), atol=1e-5)


def test_dynamic_rnn_variable_lengths_masks():
    """Shorter sequences freeze their memory and zero their outputs past
    their length."""
    D = 4
    lens = [5, 3, 5]
    rng = np.random.RandomState(1)
    flat = rng.randn(sum(lens), D).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, D], dtype="float32",
                        append_batch_size=False, lod_level=1)
        drnn = layers.DynamicRNN(maxlen=5)
        with drnn.block():
            xt = drnn.step_input(x)
            h = drnn.memory(shape=[D], value=0.0, batch_ref=xt)
            nh = layers.elementwise_add(h, xt)
            drnn.update_memory(h, nh)
            drnn.output(nh)
        out = drnn()
    (ov,) = _run(main, {"x": fluid.create_lod_tensor(flat, [lens])}, [out])
    ptr = 0
    for b, L in enumerate(lens):
        ref = np.cumsum(flat[ptr:ptr + L], axis=0)
        ptr += L
        np.testing.assert_allclose(ov[b, :L], ref, atol=1e-5)
        np.testing.assert_allclose(ov[b, L:], 0.0)


def test_ifelse_matches_rowwise_select():
    """IfElse (reference control_flow.py:2078) == where(cond, true_fn,
    false_fn) row-wise."""
    B, D = 4, 3
    rng = np.random.RandomState(2)
    xv = rng.randn(B, D).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, D], dtype="float32",
                        append_batch_size=False)
        thr = layers.fill_constant([B, 1], "float32", 0.0)
        row = layers.reduce_sum(x, dim=1, keep_dim=True)
        cond = layers.greater_than(row, thr)
        ie = layers.IfElse(cond)
        with ie.true_block():
            d = ie.input(x)
            ie.output(d * 2.0)
        with ie.false_block():
            d = ie.input(x)
            ie.output(d - 1.0)
        merged, = ie()
    (mv,) = _run(main, {"x": xv}, [merged])
    mask = xv.sum(1, keepdims=True) > 0
    np.testing.assert_allclose(mv, np.where(mask, xv * 2.0, xv - 1.0),
                               atol=1e-6)
