"""OpTest base: numpy-referenced single-op tests with numeric gradient checks.

Parity: reference ``python/paddle/fluid/tests/unittests/op_test.py:135`` —
build a one-op Program, execute, compare against a numpy reference
(`check_output`), and compare analytic grads (autodiff op) against central
finite differences (`check_grad`).
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


class OpTest:
    """Subclasses set: op_type, inputs (dict name->ndarray), attrs,
    and either outputs (dict name->ndarray) or a compute() method."""

    op_type = None
    inputs = {}
    attrs = {}
    outputs = {}

    def _build(self, extra_fetch=None):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            in_names = {}
            feed = {}
            for slot, value in self.inputs.items():
                if isinstance(value, list):  # multi-var slot
                    names = []
                    for i, v in enumerate(value):
                        v = np.asarray(v)
                        n = "%s_%s_%d" % (self.op_type, slot, i)
                        block.create_var(name=n, shape=v.shape, dtype=v.dtype,
                                         is_data=True, stop_gradient=False)
                        feed[n] = v
                        names.append(n)
                    in_names[slot] = names
                else:
                    value = np.asarray(value)
                    n = "%s_%s" % (self.op_type, slot)
                    block.create_var(name=n, shape=value.shape, dtype=value.dtype,
                                     is_data=True, stop_gradient=False)
                    feed[n] = value
                    in_names[slot] = [n]
            out_names = {}
            for slot, value in self.outputs.items():
                if isinstance(value, list):
                    names = []
                    for i, v in enumerate(value):
                        n = "%s_out_%s_%d" % (self.op_type, slot, i)
                        block.create_var(name=n, shape=(), dtype=np.asarray(v).dtype)
                        names.append(n)
                    out_names[slot] = names
                else:
                    n = "%s_out_%s" % (self.op_type, slot)
                    block.create_var(name=n, shape=(),
                                     dtype=np.asarray(value).dtype)
                    out_names[slot] = [n]
            block.append_op(self.op_type, inputs=in_names, outputs=out_names,
                            attrs=self.attrs)
        return main, startup, feed, in_names, out_names

    def check_output(self, atol=1e-5, rtol=1e-5):
        main, startup, feed, _, out_names = self._build()
        exe = fluid.Executor()
        fetch = []
        expected = []
        for slot, value in self.outputs.items():
            if isinstance(value, list):
                for n, v in zip(out_names[slot], value):
                    fetch.append(n)
                    expected.append(np.asarray(v))
            else:
                fetch.append(out_names[slot][0])
                expected.append(np.asarray(value))
        with fluid.scope_guard(fluid.Scope()):
            results = exe.run(main, feed=feed, fetch_list=fetch)
        for got, want, name in zip(results, expected, fetch):
            np.testing.assert_allclose(
                np.asarray(got, dtype=np.float64) if got.dtype != np.bool_ else got,
                np.asarray(want, dtype=np.float64) if np.asarray(want).dtype != np.bool_ else want,
                atol=atol, rtol=rtol,
                err_msg="output %s of op %s" % (name, self.op_type),
            )

    def check_grad(self, inputs_to_check, output_name, atol=5e-3, rtol=5e-3,
                   delta=1e-3):
        main, startup, feed, in_names, out_names = self._build()
        block = main.global_block()
        # find the flat output var name
        out_var = None
        for slot, names in out_names.items():
            for n in names:
                if slot == output_name or n.endswith("_" + output_name):
                    out_var = n
        if out_var is None:
            out_var = out_names[output_name][0]

        wrt = ["%s_%s" % (self.op_type, s) for s in inputs_to_check]
        gnames = [w + "@GRAD" for w in wrt]
        for w, g in zip(wrt, gnames):
            v = block.var(w)
            block.create_var(name=g, shape=v.shape, dtype=v.dtype,
                             stop_gradient=True)
        block.append_op(
            "autodiff",
            inputs={"Loss": [out_var]},
            outputs={"Grads": gnames},
            attrs={"loss": out_var, "wrt": wrt, "grad_names": gnames,
                   "loss_scale": 1.0},
        )
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            analytic = exe.run(main, feed=feed, fetch_list=gnames)

        # numeric: central differences on sum(output)
        def f(feed_override):
            main2, _, _, _, out_names2 = self._build()
            exe2 = fluid.Executor()
            with fluid.scope_guard(fluid.Scope()):
                (val,) = exe2.run(main2, feed=feed_override, fetch_list=[out_var])
            return float(np.sum(val))

        for w, got in zip(wrt, analytic):
            base = feed[w].astype(np.float64)
            numeric = np.zeros_like(base)
            flat = base.ravel()
            num_flat = numeric.ravel()
            for i in range(flat.size):
                for sign in (+1, -1):
                    pert = dict(feed)
                    b = base.copy().ravel()
                    b[i] += sign * delta
                    pert[w] = b.reshape(base.shape).astype(feed[w].dtype)
                    num_flat[i] += sign * f(pert)
                num_flat[i] /= 2 * delta
            np.testing.assert_allclose(
                got, numeric, atol=atol, rtol=rtol,
                err_msg="grad wrt %s of op %s" % (w, self.op_type),
            )
