"""FleetUtil / fleet_barrier_util (reference incubate/fleet/utils/).

Pins: global AUC from real auc-op stat buckets against sklearn-free
numpy AUC, set_zero, day/pass model save/load round trip with donefile
tracking, online-pass scheduling, and the filesystem barrier with epoch
isolation.
"""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.incubate.fleet.utils import FleetUtil
from paddle_tpu.fluid.incubate.fleet.utils.fleet_barrier_util import (
    check_all_trainers_ready)


def _auc_numpy(scores, labels):
    """Exact pairwise AUC (ties at 0.5)."""
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    return (wins + 0.5 * ties) / (len(pos) * len(neg))


def test_global_auc_matches_pairwise():
    rng = np.random.RandomState(0)
    scores = rng.rand(512).astype(np.float32)
    labels = (rng.rand(512) < scores).astype(np.int64)  # informative

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = layers.data("p", shape=[1])
        l = layers.data("l", shape=[1], dtype="int64")
        pred2 = layers.concat([1.0 - p, p], axis=1)
        auc_out, stats = layers.auc(pred2, l, num_thresholds=2**12 - 1)
    exe = fluid.Executor()
    util = FleetUtil()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"p": scores.reshape(-1, 1),
                            "l": labels.reshape(-1, 1)},
                fetch_list=[auc_out])
        got = util.get_global_auc(scope, stats[0].name, stats[1].name)
        expect = _auc_numpy(scores, labels)
        assert abs(got - expect) < 2e-3
        printed = util.print_global_auc(scope, stats[0].name, stats[1].name,
                                        print_prefix="[test]")
        assert printed == got
        # a reducer that doubles the buckets must not change the AUC
        same = util.get_global_auc(scope, stats[0].name, stats[1].name,
                                   reducer=lambda a: a * 2)
        assert abs(same - got) < 1e-9
        # set_zero resets the buckets -> degenerate AUC 0.5
        util.set_zero(stats[0].name, scope, param_type="float32")
        util.set_zero(stats[1].name, scope, param_type="float32")
        assert util.get_global_auc(scope, stats[0].name,
                                   stats[1].name) == 0.5
    # absent buckets -> None
    assert util.get_global_auc(fluid.Scope(), "nope_pos", "nope_neg") is None


def test_day_pass_model_roundtrip(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, size=3)
    exe = fluid.Executor()
    util = FleetUtil()
    out = str(tmp_path / "models")
    os.makedirs(out)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        w0 = np.asarray(exe.run(main, feed={"x": np.ones((1, 4), np.float32)},
                                fetch_list=[y])[0])
        d = util.save_model(out, "20260731", 3, exe, main)
        assert d.endswith(os.path.join("20260731", "delta-3"))
    day, pass_id, model_dir = util.get_last_save_model(out)
    assert (day, pass_id) == ("20260731", "3") and model_dir == d
    # fresh scope: load restores the exact params
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        util.load_model(out, "20260731", 3, exe, main)
        w1 = np.asarray(exe.run(main, feed={"x": np.ones((1, 4), np.float32)},
                                fetch_list=[y])[0])
    np.testing.assert_allclose(w1, w0, rtol=1e-6)
    # base dir for pass -1
    assert util._model_dir(out, "d", -1).endswith(os.path.join("d", "base"))
    # empty output path -> (None, None, None)
    assert util.get_last_save_model(str(tmp_path / "empty")) == (None, None,
                                                                 None)


def test_online_pass_interval():
    util = FleetUtil()
    iv = util.get_online_pass_interval("{20190720..20190729}", "{0..23}",
                                       split_interval=30, split_per_pass=2,
                                       is_data_hourly_placed=False)
    assert len(iv) == 24  # 48 half-hour splits, 2 per pass
    assert iv[0] == ["0000", "0030"]
    assert iv[-1] == ["2300", "2330"]
    # hourly placement + restricted hours
    iv2 = util.get_online_pass_interval(["d"], ["08", "09"], 60, 1, True)
    assert iv2 == [["08"], ["09"]]


def test_rank0_logging(capsys):
    class _F:
        def worker_index(self):
            return 1

    FleetUtil(fleet=_F()).rank0_print("must not appear")

    class _F0:
        def worker_index(self):
            return 0

    FleetUtil(fleet=_F0()).rank0_print("must appear")
    outerr = capsys.readouterr()
    assert "must appear" in outerr.out
    assert "must not appear" not in outerr.out


def test_barrier_epoch_isolation(tmp_path):
    class _Fleet:
        def __init__(self, rank, n):
            self._r, self._n = rank, n

        def worker_index(self):
            return self._r

        def worker_num(self):
            return self._n

    ready = str(tmp_path / "ready")
    # 2 trainers, epoch 0: first rank alone times out
    with pytest.raises(TimeoutError):
        check_all_trainers_ready(ready, 0, fleet=_Fleet(0, 2),
                                 timeout=1.0, interval=0.2)
    # second rank arrives -> both markers present, returns
    check_all_trainers_ready(ready, 0, fleet=_Fleet(1, 2), timeout=5.0,
                             interval=0.1)
    # a NEW epoch must not count epoch-0 markers (the reference's
    # modulo check would have aliased here)
    with pytest.raises(TimeoutError):
        check_all_trainers_ready(ready, 1, fleet=_Fleet(0, 2),
                                 timeout=1.0, interval=0.2)


def test_barrier_run_isolation(tmp_path):
    """A restarted job with a NEW run id never counts the old run's
    markers (review: stale-marker passthrough)."""
    class _Fleet:
        def __init__(self, rank, n):
            self._r, self._n = rank, n

        def worker_index(self):
            return self._r

        def worker_num(self):
            return self._n

    ready = str(tmp_path / "ready")
    # rank 0 uploads its runA marker, then times out alone
    with pytest.raises(TimeoutError):
        check_all_trainers_ready(ready, 0, fleet=_Fleet(0, 2), run_id="runA",
                                 timeout=1.0, interval=0.2)
    # rank 1 arrives: both runA markers present -> returns
    check_all_trainers_ready(ready, 0, fleet=_Fleet(1, 2), run_id="runA",
                             timeout=5.0, interval=0.1)
    # restart as runB: runA's two markers must NOT satisfy the barrier
    with pytest.raises(TimeoutError):
        check_all_trainers_ready(ready, 0, fleet=_Fleet(0, 2), run_id="runB",
                                 timeout=1.0, interval=0.2)


def test_global_auc_zero_config_discovery():
    """With no bucket names, the single layers.auc pair in the scope is
    found automatically (review: the previous defaults could never
    match generated names)."""
    rng = np.random.RandomState(1)
    scores = rng.rand(128).astype(np.float32)
    labels = (rng.rand(128) < scores).astype(np.int64)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = layers.data("p", shape=[1])
        l = layers.data("l", shape=[1], dtype="int64")
        pred2 = layers.concat([1.0 - p, p], axis=1)
        auc_out, stats = layers.auc(pred2, l)
    exe = fluid.Executor()
    util = FleetUtil()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"p": scores.reshape(-1, 1),
                            "l": labels.reshape(-1, 1)},
                fetch_list=[auc_out])
        auto = util.get_global_auc(scope)
        named = util.get_global_auc(scope, stats[0].name, stats[1].name)
        assert auto == named is not None
        # print_global_auc forwards the reducer
        doubled = util.print_global_auc(scope, reducer=lambda a: a * 2)
        assert abs(doubled - named) < 1e-9


def test_save_model_inference_mode(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, size=2)
    exe = fluid.Executor()
    util = FleetUtil()
    out = str(tmp_path / "m")
    os.makedirs(out)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        d = util.save_model(out, "20260731", -1, exe, main,
                            feeded_var_names=["x"], target_vars=[y])
        assert os.path.exists(os.path.join(d, "__model__"))
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        assert feeds == ["x"]
