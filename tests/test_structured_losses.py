"""CRF / CTC / edit-distance / sampled losses — reference
``linear_chain_crf_op.cc``, ``warpctc_op.cc``, ``edit_distance_op.cc``,
``nce_op.cc``, ``hierarchical_sigmoid_op.cc``, ``sample_logits``.
Numpy-referenced per SURVEY §4.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer


def _np_crf_logZ(em, start, end, T):
    """Brute-force partition over all paths for one sequence."""
    L, K = em.shape
    import itertools

    scores = []
    for path in itertools.product(range(K), repeat=L):
        s = start[path[0]] + em[0, path[0]]
        for t in range(1, L):
            s += T[path[t - 1], path[t]] + em[t, path[t]]
        s += end[path[-1]]
        scores.append(s)
    m = max(scores)
    return m + np.log(np.sum(np.exp(np.array(scores) - m)))


def _np_crf_path_score(em, start, end, T, labels):
    s = start[labels[0]] + em[0, labels[0]]
    for t in range(1, len(labels)):
        s += T[labels[t - 1], labels[t]] + em[t, labels[t]]
    return s + end[labels[-1]]


def test_linear_chain_crf_matches_bruteforce():
    K, lens = 3, [3, 2]
    total = sum(lens)
    rng = np.random.RandomState(0)
    emv = rng.randn(total, K).astype(np.float32)
    labv = rng.randint(0, K, (total, 1)).astype(np.int64)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        em = layers.data("em", shape=[K], dtype="float32", lod_level=1)
        lab = layers.data("lab", shape=[1], dtype="int64", lod_level=1)
        ll = layers.linear_chain_crf(
            em, lab, param_attr=fluid.ParamAttr(name="crf_T"))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (r,) = exe.run(main, feed={
            "em": fluid.create_lod_tensor(emv, [lens]),
            "lab": fluid.create_lod_tensor(labv, [lens])}, fetch_list=[ll])
        trans = np.asarray(fluid.global_scope().find_var("crf_T"))
    start, end, T = trans[0], trans[1], trans[2:]
    r = np.asarray(r).ravel()
    offs = [0] + list(np.cumsum(lens))
    for i, L in enumerate(lens):
        e = emv[offs[i]:offs[i + 1]]
        lbl = labv[offs[i]:offs[i + 1], 0]
        expect = _np_crf_path_score(e, start, end, T, lbl) - \
            _np_crf_logZ(e, start, end, T)
        np.testing.assert_allclose(r[i], expect, rtol=1e-4)


def test_crf_decoding_matches_bruteforce():
    K, lens = 3, [3, 2]
    total = sum(lens)
    rng = np.random.RandomState(1)
    emv = rng.randn(total, K).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        em = layers.data("em", shape=[K], dtype="float32", lod_level=1)
        lab = layers.data("lab", shape=[1], dtype="int64", lod_level=1)
        ll = layers.linear_chain_crf(
            em, lab, param_attr=fluid.ParamAttr(name="crf_T2"))
        path = layers.crf_decoding(em, fluid.ParamAttr(name="crf_T2"))
    exe = fluid.Executor()
    labv = np.zeros((total, 1), np.int64)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (pv,) = exe.run(main, feed={
            "em": fluid.create_lod_tensor(emv, [lens]),
            "lab": fluid.create_lod_tensor(labv, [lens])},
            fetch_list=[path])
        trans = np.asarray(fluid.global_scope().find_var("crf_T2"))
    start, end, T = trans[0], trans[1], trans[2:]
    pv = np.asarray(pv).ravel()
    import itertools

    offs = [0] + list(np.cumsum(lens))
    for i, L in enumerate(lens):
        e = emv[offs[i]:offs[i + 1]]
        best = max(itertools.product(range(K), repeat=L),
                   key=lambda p: _np_crf_path_score(e, start, end, T, p))
        np.testing.assert_array_equal(pv[offs[i]:offs[i + 1]], best)


def test_crf_trains_to_fit():
    """CRF log-likelihood increases under SGD on a fixed tiny batch."""
    K, lens = 4, [3, 3]
    total = sum(lens)
    rng = np.random.RandomState(2)
    emv = rng.randn(total, K).astype(np.float32)
    labv = rng.randint(0, K, (total, 1)).astype(np.int64)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        em = layers.data("em", shape=[K], dtype="float32", lod_level=1)
        lab = layers.data("lab", shape=[1], dtype="int64", lod_level=1)
        feat = layers.fc(em, size=K, bias_attr=False)
        ll = layers.linear_chain_crf(
            feat, lab, param_attr=fluid.ParamAttr(name="crf_T3"))
        loss = layers.mean(layers.scale(ll, scale=-1.0))
        optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    feed = {"em": fluid.create_lod_tensor(emv, [lens]),
            "lab": fluid.create_lod_tensor(labv, [lens])}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(8)]
    assert losses[-1] < losses[0]


def test_warpctc_loss_and_grads():
    """CTC via optax: loss is finite, decreases under training, and equals
    -log P(labels) for a hand-checkable case."""
    V = 4  # classes incl. blank 0
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[V], dtype="float32", lod_level=1)
        lab = layers.data("lab", shape=[1], dtype="int64", lod_level=1)
        logits = layers.fc(x, size=V, bias_attr=False)
        loss_v = layers.warpctc(logits, lab, blank=0)
        loss = layers.mean(loss_v)
        optimizer.Adam(0.05).minimize(loss)
    rng = np.random.RandomState(4)
    xv = rng.randn(8, V).astype(np.float32)          # two seqs: 5 + 3
    labv = np.array([[1], [2], [1], [3]], np.int64)  # labels: [1,2], [1,3]
    feed = {"x": fluid.create_lod_tensor(xv, [[5, 3]]),
            "lab": fluid.create_lod_tensor(labv, [[2, 2]])}
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(10)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_ctc_greedy_decoder():
    V = 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[V], dtype="float32", lod_level=1)
        out = layers.ctc_greedy_decoder(x, blank=0)
        pooled = layers.sequence_pool(
            layers.cast(out, "float32"), "sum")
    # seq1 argmax: [1,1,0,2] -> collapse/deblank -> [1,2]
    # seq2 argmax: [3,0,3] -> [3,3]
    def row(i):
        r = np.zeros(V, np.float32)
        r[i] = 5.0
        return r

    xv = np.stack([row(1), row(1), row(0), row(2),
                   row(3), row(0), row(3)]).astype(np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        ov, pv = exe.run(main, feed={
            "x": fluid.create_lod_tensor(xv, [[4, 3]])},
            fetch_list=[out, pooled])
    ov = np.asarray(ov).ravel()
    assert ov[0] == 1 and ov[1] == 2
    np.testing.assert_allclose(np.asarray(pv).ravel(), [3.0, 6.0])


def test_edit_distance():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        hyp = layers.data("hyp", shape=[1], dtype="int64", lod_level=1)
        ref = layers.data("ref", shape=[1], dtype="int64", lod_level=1)
        dist, seq_num = layers.edit_distance(hyp, ref, normalized=False)
    # pair 1: kitten->sitting analog [1,2,3] vs [1,3,3,4] = 2
    # pair 2: [5] vs [5] = 0
    hv = np.array([[1], [2], [3], [5]], np.int64)
    rv = np.array([[1], [3], [3], [4], [5]], np.int64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        dv, nv = exe.run(main, feed={
            "hyp": fluid.create_lod_tensor(hv, [[3, 1]]),
            "ref": fluid.create_lod_tensor(rv, [[4, 1]])},
            fetch_list=[dist, seq_num])
    np.testing.assert_allclose(np.asarray(dv).ravel(), [2.0, 0.0])
    assert int(np.asarray(nv)) == 2


def test_nce_trains():
    B, D, C = 8, 6, 20
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[D], dtype="float32")
        lab = layers.data("lab", shape=[1], dtype="int64")
        emb = layers.fc(x, size=D, act="tanh")
        cost = layers.nce(emb, lab, num_total_classes=C,
                          num_neg_samples=5)
        loss = layers.mean(cost)
        optimizer.Adam(0.05).minimize(loss)
    rng = np.random.RandomState(6)
    feed = {"x": rng.randn(B, D).astype(np.float32),
            "lab": rng.randint(0, C, (B, 1)).astype(np.int64)}
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(10)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_hsigmoid_trains_and_costs_positive():
    B, D, C = 6, 5, 10
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[D], dtype="float32")
        lab = layers.data("lab", shape=[1], dtype="int64")
        cost = layers.hsigmoid(x, lab, num_classes=C)
        loss = layers.mean(cost)
        optimizer.Adam(0.1).minimize(loss)
    rng = np.random.RandomState(8)
    feed = {"x": rng.randn(B, D).astype(np.float32),
            "lab": rng.randint(0, C, (B, 1)).astype(np.int64)}
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        first = np.asarray(exe.run(main, feed=feed,
                                   fetch_list=[cost])[0])
        assert (first > 0).all()  # -log P is positive
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(10)]
    assert losses[-1] < losses[0]


def test_sampled_softmax_trains():
    B, C = 8, 30
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 9
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[C], dtype="float32")
        lab = layers.data("lab", shape=[1], dtype="int64")
        logits = layers.fc(x, size=C, bias_attr=False)
        loss_v = layers.sampled_softmax_with_cross_entropy(
            logits, lab, num_samples=8)
        loss = layers.mean(loss_v)
        optimizer.Adam(0.05).minimize(loss)
    rng = np.random.RandomState(10)
    feed = {"x": rng.randn(B, C).astype(np.float32),
            "lab": rng.randint(0, C, (B, 1)).astype(np.int64)}
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(12)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_warpctc_padded_api_matches_lod():
    """warpctc(input_length=, label_length=) over dense [B,T,V]/[B,N] must
    equal the LoD path on the same data."""
    V, B = 4, 2
    rng = np.random.RandomState(30)
    dense_logits = rng.randn(B, 5, V).astype(np.float32)
    dense_labels = np.array([[1, 2], [3, 1]], np.int64)
    llen = np.array([[5], [3]], np.int64)
    tlen = np.array([[2], [2]], np.int64)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lg = layers.data("lg", shape=[5, V], dtype="float32")
        lb = layers.data("lb", shape=[2], dtype="int64")
        il = layers.data("il", shape=[1], dtype="int64")
        ll = layers.data("ll", shape=[1], dtype="int64")
        loss_p = layers.warpctc(lg, lb, blank=0, input_length=il,
                                label_length=ll)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (pv,) = exe.run(main, feed={"lg": dense_logits, "lb": dense_labels,
                                    "il": llen, "ll": tlen},
                        fetch_list=[loss_p])
    # LoD path on the flattened equivalent
    flat = np.concatenate([dense_logits[0, :5], dense_logits[1, :3]])
    flab = dense_labels.reshape(-1, 1)
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        lg2 = layers.data("lg2", shape=[V], dtype="float32", lod_level=1)
        lb2 = layers.data("lb2", shape=[1], dtype="int64", lod_level=1)
        loss_l = layers.warpctc(lg2, lb2, blank=0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup2)
        (lv,) = exe.run(main2, feed={
            "lg2": fluid.create_lod_tensor(flat, [[5, 3]]),
            "lb2": fluid.create_lod_tensor(flab, [[2, 2]])},
            fetch_list=[loss_l])
    np.testing.assert_allclose(np.asarray(pv).ravel(),
                               np.asarray(lv).ravel(), rtol=1e-4)


def test_edit_distance_padded_api():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        hyp = layers.data("hyp", shape=[3], dtype="int64")
        ref = layers.data("ref", shape=[4], dtype="int64")
        hl = layers.data("hl", shape=[1], dtype="int64")
        rl = layers.data("rl", shape=[1], dtype="int64")
        dist, _ = layers.edit_distance(hyp, ref, normalized=False,
                                       input_length=hl, label_length=rl)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (dv,) = exe.run(main, feed={
            "hyp": np.array([[1, 2, 3], [5, 0, 0]], np.int64),
            "ref": np.array([[1, 3, 3, 4], [5, 0, 0, 0]], np.int64),
            "hl": np.array([[3], [1]], np.int64),
            "rl": np.array([[4], [1]], np.int64)}, fetch_list=[dist])
    np.testing.assert_allclose(np.asarray(dv).ravel(), [2.0, 0.0])


def test_nce_unsupported_sampler_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        lab = layers.data("lab", shape=[1], dtype="int64")
        with pytest.raises(NotImplementedError):
            layers.nce(x, lab, num_total_classes=10,
                       sampler="custom_dist", custom_dist=[0.1] * 10)
        with pytest.raises(NotImplementedError):
            layers.sampled_softmax_with_cross_entropy(
                x, lab, num_samples=2, remove_accidental_hits=False)
