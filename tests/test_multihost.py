"""Multi-host SPMD runtime — the TCP coordination service
(distributed/coordination.py), the TcpRendezvous built on it, the
launcher's coord-port handling, and the hierarchical DCN
data-parallelism layer (c_hierarchical_allreduce /
HierarchicalGradAllReduce / parallel.cross_host), ending in a 2-process
fake cluster bootstrapped with no shared filesystem at all."""

import json
import os
import re
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "tests", "dist_runner_multihost.py")

from paddle_tpu.distributed import coordination, rendezvous, wire  # noqa: E402
from paddle_tpu.fluid import monitor  # noqa: E402


# -- wire framing (satellite: shared framed-TCP plumbing) --------------------

def test_wire_frame_roundtrip():
    a, b = socket.socketpair()
    try:
        wire.send_all(a, wire.frame(b"hello" * 100))
        assert wire.read_frame(b) == b"hello" * 100
    finally:
        a.close()
        b.close()


def test_wire_frame_too_large_is_connection_error():
    a, b = socket.socketpair()
    try:
        wire.send_all(a, wire.frame(b"x" * 1000))
        with pytest.raises(wire.FrameTooLarge):
            wire.read_frame(b, max_bytes=100)
        assert issubclass(wire.FrameTooLarge, ConnectionError), \
            "an oversized frame leaves the stream unsyncable"
    finally:
        a.close()
        b.close()


def test_wire_peer_close_mid_frame():
    a, b = socket.socketpair()
    a.sendall(b"\x10\x00\x00\x00abc")  # 16-byte frame, 3 bytes sent
    a.close()
    try:
        with pytest.raises(ConnectionError):
            wire.read_frame(b)
    finally:
        b.close()


# -- coordination service ----------------------------------------------------

@pytest.fixture
def coord():
    srv = coordination.CoordServer().start()
    client = coordination.CoordClient(srv.endpoint)
    yield srv, client
    client.close()
    srv.stop()


def test_coord_kv_roundtrip(coord):
    _, c = coord
    assert c.get("missing") is None
    c.put("k", b"v1")
    assert c.get("k") == b"v1"
    c.put("k", "v2")  # str values encode transparently
    assert c.get("k") == b"v2"
    assert sorted(c.keys("")) == ["k"]
    assert c.delete("k") is True
    assert c.delete("k") is False  # atomic claim: second deleter loses
    assert c.get("k") is None


def test_coord_fetch_add_interops_with_get(coord):
    _, c = coord
    assert c.add("ctr", 1) == 1
    assert c.add("ctr", 2) == 3
    # the counter is stored as ascii so plain get() reads it too
    assert int(c.get("ctr")) == 3


def test_coord_wait_get_blocks_until_put(coord):
    srv, c = coord
    other = coordination.CoordClient(srv.endpoint)
    try:
        t = threading.Thread(
            target=lambda: (time.sleep(0.2), other.put("late", b"ok")))
        t.start()
        t0 = time.monotonic()
        assert c.get("late", wait=True, timeout=10.0) == b"ok"
        assert time.monotonic() - t0 < 9.0  # woke on the put, not timeout
        t.join()
    finally:
        other.close()


def test_coord_barrier_releases_at_world(coord):
    srv, _ = coord
    gens = []

    def member(cid):
        cl = coordination.CoordClient(srv.endpoint)
        try:
            gens.append(cl.barrier("step", world=2, client_id=cid,
                                   timeout=30.0))
        finally:
            cl.close()

    ts = [threading.Thread(target=member, args=("m%d" % i,))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert gens == [1, 1]


def test_coord_barrier_arrival_is_idempotent(coord):
    srv, c = coord
    # the same client id arriving twice must NOT release a world-2
    # barrier (transport retries would otherwise double-count)
    with pytest.raises(TimeoutError):
        c.barrier("dup", world=2, client_id="only", timeout=0.5)
    with pytest.raises(TimeoutError):
        c.barrier("dup", world=2, client_id="only", timeout=0.5)
    # "only" stays registered server-side; one DISTINCT id completes
    # the world-2 barrier immediately
    other = coordination.CoordClient(srv.endpoint)
    try:
        assert other.barrier("dup", world=2, client_id="late",
                             timeout=30.0) == 1
    finally:
        other.close()


def test_coord_broadcast(coord):
    srv, c = coord
    got = []
    other = coordination.CoordClient(srv.endpoint)
    try:
        t = threading.Thread(
            target=lambda: got.append(other.broadcast("blob",
                                                      timeout=30.0)))
        t.start()
        assert c.broadcast("blob", value=b"payload") == b"payload"
        t.join(timeout=60)
        assert got == [b"payload"]
    finally:
        other.close()


def test_coord_lease_liveness(coord):
    _, c = coord
    c.lease("w0", ttl=30.0)
    c.lease("w1", ttl=0.2)
    assert "w0" in c.live() and "w1" in c.live()
    time.sleep(0.4)
    live = c.live()
    assert "w0" in live and "w1" not in live  # expired lease pruned


def test_coord_wrong_token_rejected():
    srv = coordination.CoordServer(token="sesame").start()
    try:
        # the handshake happens at connect time, so construction raises
        with pytest.raises((ConnectionError, RuntimeError)):
            coordination.CoordClient(srv.endpoint, token="wrong").ping()
        ok = coordination.CoordClient(srv.endpoint, token="sesame")
        try:
            ok.ping()
        finally:
            ok.close()
    finally:
        srv.stop()


def test_coord_malformed_payload_keeps_server_alive(coord):
    _, c = coord
    with pytest.raises(RuntimeError):
        # opcode PUT with a truncated key header -> typed decode error
        # frame, NOT a dropped connection
        c._conn.request(b"\x01\xff")
    c.put("still", b"alive")
    assert c.get("still") == b"alive"


def test_coord_metrics_registered(coord):
    _, c = coord
    c.put("m", b"1")
    c.get("m")
    dump = monitor.dump_json()
    for name in ("coord_puts_total", "coord_gets_total",
                 "coord_barriers_total", "coord_barrier_wait_seconds",
                 "coord_watch_clients"):
        assert name in dump, name
    assert dump["coord_puts_total"][0]["value"] >= 1
    assert dump["coord_gets_total"][0]["value"] >= 1


# -- TcpRendezvous (satellite: file backend stays, TCP added) ----------------

@pytest.fixture
def tcp_rdzv():
    srv = coordination.CoordServer().start()
    r = rendezvous.TcpRendezvous(addr=srv.endpoint)
    yield r
    r.close()
    srv.stop()


def test_tcp_rendezvous_world_roundtrip(tcp_rdzv):
    assert tcp_rdzv.world() is None
    tcp_rdzv.record_world(2, generation=3)
    w = tcp_rdzv.world()
    assert w["world_size"] == 2
    assert w["slots"] == [0, 1]
    assert tcp_rdzv.generation() == 3


def test_tcp_rendezvous_slot_claim_is_atomic(tcp_rdzv):
    tcp_rdzv.offer_slot(1)
    tcp_rdzv.offer_slot(2)
    assert sorted(tcp_rdzv.returned_slots()) == [1, 2]
    assert sorted(tcp_rdzv.consume_slots()) == [1, 2]
    assert tcp_rdzv.consume_slots() == []  # second consumer gets nothing


def test_tcp_rendezvous_members(tcp_rdzv, monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    tcp_rdzv.announce(rank=0, step=5)
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    tcp_rdzv.announce(rank=1, step=5)
    members = tcp_rdzv.members()
    assert sorted(members) == [0, 1]
    assert members[1]["step"] == 5
    tcp_rdzv.clear_members()
    assert tcp_rdzv.members() == {}


def test_rendezvous_create_backend_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv(coordination.ENV_BACKEND, raising=False)
    monkeypatch.delenv(coordination.ENV_ADDR, raising=False)
    r = rendezvous.create(backend="file", dirname=str(tmp_path))
    assert isinstance(r, rendezvous.Rendezvous)
    srv = coordination.CoordServer().start()
    try:
        r = rendezvous.create(backend="tcp", addr=srv.endpoint)
        assert isinstance(r, rendezvous.TcpRendezvous)
        r.close()
        # env-driven: PADDLE_COORD_BACKEND/ADDR select TCP
        monkeypatch.setenv(coordination.ENV_BACKEND, "tcp")
        monkeypatch.setenv(coordination.ENV_ADDR, srv.endpoint)
        r = rendezvous.create()
        assert isinstance(r, rendezvous.TcpRendezvous)
        r.close()
    finally:
        srv.stop()
    with pytest.raises(ValueError):
        rendezvous.create(backend="carrier-pigeon")


# -- launcher coord-port handling (satellite: port-range regression) ---------

def test_coord_server_bind_race_picks_fresh_base(monkeypatch):
    """A lost bind race on the coordination port retries with a FRESH
    base, counting launch_port_retries_total but never the restart
    budget (the server starts before any worker spawn)."""
    from paddle_tpu.distributed import launch as L

    nproc = 2
    blocker = socket.socket()  # bind-only blocker forcing the collision
    blocker.bind(("127.0.0.1", 0))
    taken = blocker.getsockname()[1]
    good = wire.reserve_port_range(nproc + 1)
    bases = [taken - nproc, good]  # first base -> coord port collides
    monkeypatch.setattr(
        L, "_reserve_port_range",
        lambda n, tries=10, extra=0: bases.pop(0))
    retries_before = L._M_PORT_RETRIES.value
    restarts_before = L._M_RESTARTS.value
    try:
        srv, base = L._start_coord_server("127.0.0.1", nproc,
                                          started_port=None, port_retries=3)
    finally:
        blocker.close()
    try:
        assert base == good
        c = coordination.CoordClient(srv.endpoint)
        c.ping()
        c.close()
    finally:
        srv.stop()
    assert L._M_PORT_RETRIES.value == retries_before + 1
    assert L._M_RESTARTS.value == restarts_before  # budget untouched


def test_coord_server_explicit_port_does_not_retry(monkeypatch):
    """--started_port pins the range: a bind failure there must raise,
    not silently migrate the gang to other ports."""
    from paddle_tpu.distributed import launch as L

    blocker = socket.socket()  # bind-only port blocker for the test
    blocker.bind(("127.0.0.1", 0))
    taken = blocker.getsockname()[1]
    try:
        with pytest.raises(OSError):
            L._start_coord_server("127.0.0.1", 2, started_port=taken - 2,
                                  port_retries=5)
    finally:
        blocker.close()


# -- hierarchical collectives ------------------------------------------------

def _build_mlp(seed=7):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=8, act="relu",
                      param_attr=fluid.ParamAttr(name="hh_w1"))
        p = layers.fc(h, size=1, param_attr=fluid.ParamAttr(name="hh_w2"))
        loss = layers.mean(layers.square(p - y))
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _train(transpiler, steps=5, **compile_kw):
    import paddle_tpu.fluid as fluid

    rng = np.random.default_rng(0)
    feed = {"x": rng.normal(size=(16, 4)).astype(np.float32),
            "y": rng.normal(size=(16, 1)).astype(np.float32)}
    main, startup, loss = _build_mlp()
    transpiler.transpile(startup, main)
    compiled = fluid.CompiledProgram(main).with_explicit_collectives(
        loss_name=loss.name, **compile_kw)
    exe = fluid.Executor()
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        w = np.asarray(exe.run(compiled, feed=feed, fetch_list=["hh_w1"])[0])
    return losses, w


def test_hierarchical_transpiler_matches_flat():
    from paddle_tpu.fluid.transpiler.collective import (
        GradAllReduce, HierarchicalGradAllReduce)

    flat_l, flat_w = _train(GradAllReduce(nranks=8))
    hier_l, hier_w = _train(HierarchicalGradAllReduce(nranks=8),
                            mesh_axes=("host", "device"),
                            mesh_shape={"host": 2, "device": 4})
    np.testing.assert_allclose(hier_l, flat_l, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(hier_w, flat_w, rtol=1e-5, atol=1e-6)


def test_hierarchical_transpiler_op_mix():
    from paddle_tpu.fluid.transpiler.collective import (
        HierarchicalGradAllReduce)

    main, startup, _ = _build_mlp()
    HierarchicalGradAllReduce(nranks=8).transpile(startup, main)
    types = [op.type for op in main.global_block().ops]
    assert types.count("c_hierarchical_allreduce") == 4  # w1/b1/w2/b2
    assert "c_allreduce_sum" not in types


def test_hierarchical_dgc_splits_rings():
    """Under DGC the DENSE grad reduces in-host (ring 1 = ICI) and only
    the compressed output crosses hosts (ring 0 = DCN)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer
    from paddle_tpu.fluid.transpiler.collective import (
        HierarchicalGradAllReduce)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        p = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="dg_w"))
        loss = layers.mean(p)
        optimizer.DGCMomentumOptimizer(0.1, 0.9,
                                       sparsity=(0.75,)).minimize(loss)
    HierarchicalGradAllReduce(nranks=8).transpile(startup, main)
    ops = main.global_block().ops
    dgc_ops = [o for o in ops if o.type == "dgc"]
    assert dgc_ops, "DGC optimizer must emit dgc ops"
    dense = set()
    for o in dgc_ops:
        dense.update(o.input("Grad"))
    compressed = set()
    for o in dgc_ops:
        compressed.update(o.output("GradOut"))
    ici = [o for o in ops if o.type == "c_allreduce_sum"
           and o.attr("ring_id", 0) == 1]
    dcn = [o for o in ops if o.type == "c_allreduce_sum"
           and o.attr("ring_id", 0) == 0]
    assert {n for o in ici for n in o.input("X")} == dense
    assert {n for o in dcn for n in o.input("X")} == compressed
    assert not any(o.type == "c_hierarchical_allreduce"
                   and set(o.input("X")) & dense for o in ops)


def test_hier_psum_matches_flat_psum():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.jax_compat import shard_map
    from paddle_tpu.parallel import hier_psum, make_host_device_mesh

    mesh = make_host_device_mesh(2, 4)
    x = np.arange(8 * 5, dtype=np.float32).reshape(8, 5) * 0.25

    def hier(v):
        return hier_psum(v)

    def flat(v):
        return jax.lax.psum(v, ("host", "device"))

    kw = dict(mesh=mesh, in_specs=P(("host", "device")), out_specs=P(),
              check_vma=False)
    got = shard_map(hier, **kw)(jnp.asarray(x))
    want = shard_map(flat, **kw)(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5)


def test_feed_sharding_spans_both_mesh_axes():
    import paddle_tpu.fluid as fluid

    main, _, loss = _build_mlp()
    compiled = fluid.CompiledProgram(main).with_explicit_collectives(
        loss_name=loss.name, mesh_axes=("host", "device"),
        mesh_shape={"host": 2, "device": 4})
    sh = compiled.feed_sharding(np.zeros((16, 3), np.float32))
    assert sh.spec[0] == ("host", "device")
    # batch only divisible by the host axis: leading-axis fallback
    sh = compiled.feed_sharding(np.zeros((4, 3), np.float32))
    assert sh.spec[0] == "host"
    # batch divisible by neither: replicated
    sh = compiled.feed_sharding(np.zeros((3, 3), np.float32))
    assert not any(sh.spec)


# -- CrossHostGradSync -------------------------------------------------------

def test_crosshost_allreduce_matches_flat_mean():
    from paddle_tpu.parallel import CrossHostGradSync

    rng = np.random.default_rng(1)
    grads = [rng.normal(size=(2, 4, 3, 5)).astype(np.float32),
             rng.normal(size=(2, 4, 7)).astype(np.float32)]
    sync = CrossHostGradSync(hosts=2, devices_per_host=4)
    out = sync.allreduce(grads)
    for g, o in zip(grads, out):
        want = np.broadcast_to(g.mean(axis=(0, 1), keepdims=True), g.shape)
        np.testing.assert_allclose(np.asarray(o), want, rtol=1e-5,
                                   atol=1e-6)


def test_crosshost_allreduce_local_is_per_host():
    from paddle_tpu.parallel import CrossHostGradSync

    rng = np.random.default_rng(2)
    g = rng.normal(size=(2, 4, 6)).astype(np.float32)
    sync = CrossHostGradSync(hosts=2, devices_per_host=4)
    (o,) = sync.allreduce_local([g])
    want = np.broadcast_to(g.mean(axis=1, keepdims=True), g.shape)
    np.testing.assert_allclose(np.asarray(o), want, rtol=1e-5, atol=1e-6)


def test_crosshost_dgc_compresses_dcn_only():
    from paddle_tpu.parallel import CrossHostGradSync

    monitor.reset()
    rng = np.random.default_rng(3)
    g = rng.normal(size=(2, 4, 64)).astype(np.float32)
    sync = CrossHostGradSync(hosts=2, devices_per_host=4, dgc_ratio=0.25)
    (o1,) = sync.allreduce([g])
    (o2,) = sync.allreduce([g])  # residuals carry across steps
    assert np.isfinite(np.asarray(o1)).all()
    assert np.isfinite(np.asarray(o2)).all()
    dump = monitor.dump_json()
    by_phase = {e["labels"]["phase"]: e
                for e in dump["crosshost_allreduce_bytes_total"]}
    # DCN bytes are ratio-scaled; ICI stays dense
    assert by_phase["dcn"]["value"] < by_phase["ici"]["value"]


def test_crosshost_localsgd_sync_cadence():
    from paddle_tpu.parallel import CrossHostGradSync

    rng = np.random.default_rng(4)
    p = rng.normal(size=(2, 4, 5)).astype(np.float32)
    sync = CrossHostGradSync(hosts=2, devices_per_host=4,
                             local_sgd_steps=3)
    params = [p]
    assert sync.localsgd_params(params, step=0) is params  # off-step
    assert sync.localsgd_params(params, step=1) is params
    (o,) = sync.localsgd_params(params, step=2)  # (2+1) % 3 == 0
    want = np.broadcast_to(p.mean(axis=0, keepdims=True), p.shape)
    np.testing.assert_allclose(np.asarray(o), want, rtol=1e-5, atol=1e-6)


def test_crosshost_metrics_label_phases():
    from paddle_tpu.parallel import CrossHostGradSync

    monitor.reset()
    g = np.ones((2, 2, 8), np.float32)
    CrossHostGradSync(hosts=2, devices_per_host=2).allreduce([g])
    dump = monitor.dump_json()
    for name in ("crosshost_allreduce_seconds",
                 "crosshost_allreduce_bytes_total"):
        phases = {e["labels"]["phase"] for e in dump[name]}
        assert phases == {"ici", "dcn"}, (name, phases)


# -- end-to-end: 2 hosts x 2 devices over pure TCP ---------------------------

def _hier_baseline():
    """Single-process 2x2 hierarchical run over 4 of the local devices
    — the same mesh shape the 2-process gang builds globally."""
    from paddle_tpu.fluid.transpiler.collective import (
        HierarchicalGradAllReduce)
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, optimizer
    import jax

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 23
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu",
                      param_attr=fluid.ParamAttr(name="mh_w1"))
        logits = layers.fc(h, size=4,
                           param_attr=fluid.ParamAttr(name="mh_w2"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(0.1).minimize(loss)
    HierarchicalGradAllReduce(nranks=4).transpile(startup, main)
    compiled = fluid.CompiledProgram(main).with_explicit_collectives(
        loss_name=loss.name, places=jax.devices()[:4],
        mesh_axes=("host", "device"),
        mesh_shape={"host": 2, "device": 2})
    exe = fluid.Executor()
    rng = np.random.RandomState(1)
    feed = {"x": rng.rand(16, 8).astype(np.float32),
            "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(4):
            (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    return losses


@pytest.mark.multihost
@pytest.mark.slow
def test_two_host_hierarchical_dp_matches_single_process(tmp_path):
    """2 processes x 2 devices, bootstrapped purely over the TCP
    coordination service (no PADDLE_RENDEZVOUS_DIR anywhere), must
    reproduce the single-process 4-device hierarchical run."""
    base = _hier_baseline()

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.pop("PADDLE_RENDEZVOUS_DIR", None)
    log_dir = str(tmp_path / "logs")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--backend", "cpu",
           "--rendezvous_backend", "tcp", "--log_dir", log_dir, RUNNER]
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       timeout=600)
    logs = ""
    for i in range(2):
        with open(os.path.join(log_dir, "worker.%d.log" % i)) as f:
            logs += "--- worker %d ---\n%s\n" % (i, f.read())
    assert r.returncode == 0, logs

    per_rank = re.findall(r"LOSSES (\[.*\])", logs)
    assert len(per_rank) == 2, logs
    l0, l1 = json.loads(per_rank[0]), json.loads(per_rank[1])
    np.testing.assert_allclose(l0, l1, rtol=1e-6)  # same global loss
    np.testing.assert_allclose(l0, base, rtol=1e-4)
    digests = re.findall(r"WDIGEST (\S+)", logs)
    assert len(digests) == 2, logs
    assert float(digests[0]) == float(digests[1])  # replicated params
