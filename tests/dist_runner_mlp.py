"""Fake-cluster runner (reference ``test_dist_base.py`` runner scripts):
trains a small MLP data-parallel across the processes the launcher spawned.
Prints one line: ``LOSSES <json list>`` — the parent test compares ranks
against the single-process baseline.

Run via:
  python -m paddle_tpu.distributed.launch --nproc_per_node 2 --backend cpu \
      tests/dist_runner_mlp.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.distributed import env as dist_env  # noqa: E402

rank, world = dist_env.init_parallel_env(ndev_per_proc=2)

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import layers, optimizer  # noqa: E402


def build(seed=17):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def main():
    import jax

    assert jax.process_count() == world, (jax.process_count(), world)
    main_p, startup, loss = build()
    compiled = fluid.CompiledProgram(main_p).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    # every rank feeds the same GLOBAL batch; device_put shards it over the
    # global mesh (batch 16 over 4 global devices)
    feed = {"x": rng.rand(16, 8).astype(np.float32),
            "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(4):
            (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    print("LOSSES " + json.dumps(losses), flush=True)


if __name__ == "__main__":
    main()
