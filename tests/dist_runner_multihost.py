"""Multi-host fake-cluster runner: each launched process is one "host"
of a 2-level ``(host, device)`` mesh, bootstrapped PURELY over the TCP
coordination service (the runner refuses to start if a shared-FS
rendezvous dir leaked into its env). Trains a small MLP data-parallel
with ``HierarchicalGradAllReduce`` — in-host reduce-scatter/all-gather
over the process-local devices, cross-host allreduce over the gloo
"DCN" — and prints per-step losses plus a final weight digest so the
parent test can compare against the single-process baseline.

Run via:
  python -m paddle_tpu.distributed.launch --nproc_per_node 2 --backend cpu \
      tests/dist_runner_multihost.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# pure-TCP contract: the launcher must have exported the coordination
# endpoint and must NOT have exported a shared-filesystem rendezvous dir
assert os.environ.get("PADDLE_COORD_ADDR"), \
    "runner requires a TCP coordination service (PADDLE_COORD_ADDR)"
assert "PADDLE_RENDEZVOUS_DIR" not in os.environ, \
    "shared-FS rendezvous leaked into a TCP-bootstrapped gang"

from paddle_tpu.distributed import env as dist_env  # noqa: E402

rank, world = dist_env.init_parallel_env(ndev_per_proc=2)

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import layers, optimizer  # noqa: E402
from paddle_tpu.fluid.transpiler.collective import (  # noqa: E402
    HierarchicalGradAllReduce)


def build(seed=23):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu",
                      param_attr=fluid.ParamAttr(name="mh_w1"))
        logits = layers.fc(h, size=4,
                           param_attr=fluid.ParamAttr(name="mh_w2"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def main():
    import jax

    assert jax.process_count() == world, (jax.process_count(), world)
    ndev = jax.local_device_count()
    main_p, startup, loss = build()
    HierarchicalGradAllReduce(nranks=world * ndev).transpile(startup, main_p)
    compiled = fluid.CompiledProgram(main_p).with_explicit_collectives(
        loss_name=loss.name,
        mesh_axes=("host", "device"),
        mesh_shape={"host": world, "device": ndev})
    exe = fluid.Executor()
    rng = np.random.RandomState(1)
    # every rank feeds the same GLOBAL batch; feed_sharding splits it
    # over all host*device shards of the global mesh
    feed = {"x": rng.rand(16, 8).astype(np.float32),
            "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(4):
            (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
        w = np.asarray(exe.run(compiled, feed=feed, fetch_list=["mh_w1"])[0])
    print("LOSSES " + json.dumps(losses), flush=True)
    print("WDIGEST %.10e" % float(np.abs(w).sum()), flush=True)


if __name__ == "__main__":
    main()
