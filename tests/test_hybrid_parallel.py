"""4D hybrid-parallel train step vs single-device reference: loss AND the
full post-SGD parameter tree must match — this locks in the gradient-sync
rules of paddle_tpu/parallel/hybrid.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel import hybrid, make_mesh


@pytest.fixture(scope="module")
def setup():
    cfg = hybrid.HybridConfig(vocab=128, hidden=32, n_heads=4, ffn=64,
                              layers_per_stage=1, seq_len=16, microbatches=2)
    sizes = hybrid.choose_axes(8)
    mesh = make_mesh(sizes)
    params = hybrid.init_params(cfg, n_stages=sizes["pp"],
                                tp_size=sizes["tp"], seed=0)
    ids, labels = hybrid.demo_batch(cfg, batch=4)
    return cfg, mesh, params, ids, labels


def test_choose_axes():
    assert hybrid.choose_axes(8) == {"sp": 2, "tp": 2, "pp": 2, "dp": 1}
    assert hybrid.choose_axes(16) == {"sp": 2, "tp": 2, "pp": 2, "dp": 2}
    assert hybrid.choose_axes(1) == {"sp": 1, "tp": 1, "pp": 1, "dp": 1}


@pytest.mark.slow
def test_hybrid_loss_matches_reference(setup):
    cfg, mesh, params, ids, labels = setup
    lr = 0.0  # no update: isolates the forward
    step = hybrid.make_train_step(cfg, mesh, lr=lr)
    _, loss = step(jax.tree_util.tree_map(jnp.copy, params), ids, labels)
    ref = hybrid.reference_loss(params, ids, labels, cfg)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


@pytest.mark.slow
def test_hybrid_sgd_step_matches_reference(setup):
    cfg, mesh, params, ids, labels = setup
    lr = 0.1
    step = hybrid.make_train_step(cfg, mesh, lr=lr)
    new_params, _ = step(jax.tree_util.tree_map(jnp.copy, params), ids,
                         labels)

    ref_grads = jax.grad(
        lambda p: hybrid.reference_loss(p, ids, labels, cfg))(params)
    ref_new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params,
                                     ref_grads)

    flat_a, _ = jax.tree_util.tree_flatten_with_path(new_params)
    flat_b = dict(jax.tree_util.tree_flatten_with_path(ref_new)[0])
    for path, a in flat_a:
        b = flat_b[path]
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5,
            err_msg=f"param mismatch at {jax.tree_util.keystr(path)}")


@pytest.mark.slow
def test_hybrid_training_reduces_loss(setup):
    cfg, mesh, params, ids, labels = setup
    step = hybrid.make_train_step(cfg, mesh, lr=0.1)
    p = jax.tree_util.tree_map(jnp.copy, params)
    losses = []
    for _ in range(8):
        p, loss = step(p, ids, labels)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
