"""SelectedRows sparse gradients + sparse optimizer updates — reference
``selected_rows.h:32``, ``lookup_table_op.cc`` grad kernel,
``optimizers/*`` SelectedRows paths. The TPU encoding is a (values, rows)
array pair: values bound to the grad var name, int32 rows to name+'@ROWS'."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.models import deepfm


def _build_emb_sgd(is_sparse, vocab=50, dim=4, lr=0.5, seed=3):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, dim], is_sparse=is_sparse,
                               param_attr=fluid.ParamAttr(name="emb_w"))
        loss = layers.mean(layers.reduce_sum(emb, dim=-1))
        optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss


def test_sparse_grad_var_is_selected_rows():
    main, _, _ = _build_emb_sgd(True)
    block = main.global_block()
    gvar = block.var("emb_w@GRAD")
    assert gvar.type == "selected_rows"
    assert block.var("emb_w@GRAD@ROWS") is not None
    ad = next(op for op in block.ops if op.type == "autodiff")
    assert ad.attr("sparse_wrt"), "autodiff lost the sparse marker"


def test_sparse_matches_dense_sgd():
    """is_sparse=True must train identically to the dense path (duplicate
    ids in a batch must accumulate, untouched rows must not move)."""
    feed = {"ids": np.array([[1, 2, 2], [7, 1, 1]], np.int64)}
    res = {}
    for sparse in (False, True):
        main, startup, loss = _build_emb_sgd(sparse)
        w = main.global_block().var("emb_w")
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            w0 = np.asarray(exe.run(main, feed=feed, fetch_list=[w])[0])
            for _ in range(2):
                w1 = np.asarray(exe.run(main, feed=feed, fetch_list=[w])[0])
            res[sparse] = (w0, w1)
    np.testing.assert_allclose(res[False][0], res[True][0], atol=1e-6)
    np.testing.assert_allclose(res[False][1], res[True][1], atol=1e-6)
    # untouched rows never moved
    w0, w1 = res[True]
    touched = {1, 2, 7}
    untouched = [i for i in range(50) if i not in touched]
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert np.abs(w1[sorted(touched)] - w0[sorted(touched)]).max() > 0


def test_sparse_adam_lazy_mode():
    """Sparse adam: untouched rows keep params AND moments frozen (lazy
    mode), touched rows match a dense-masked reference step."""
    vocab, dim = 20, 3
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[2], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, dim], is_sparse=True,
                               param_attr=fluid.ParamAttr(name="w_adam"))
        loss = layers.mean(layers.reduce_sum(emb * emb, dim=-1))
        optimizer.Adam(learning_rate=0.1).minimize(loss)
    w = main.global_block().var("w_adam")
    exe = fluid.Executor()
    feed = {"ids": np.array([[0, 5]], np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        w0 = np.asarray(exe.run(main, feed=feed, fetch_list=[w])[0])
        w1 = np.asarray(exe.run(main, feed=feed, fetch_list=[w])[0])
    moved = np.abs(w1 - w0).max(axis=1) > 0
    assert moved[0] and moved[5]
    assert not moved[np.setdiff1d(np.arange(vocab), [0, 5])].any()


def test_deepfm_sparse_matches_dense():
    """BASELINE config 4: DeepFM trains with sparse embedding updates and
    tracks the dense-path loss curve."""
    cfg = deepfm.DeepFMConfig.tiny()
    batch = deepfm.synthetic_batch(cfg, 32)
    curves = {}
    for sparse in (False, True):
        main, startup, loss, _ = deepfm.build_train_program(
            cfg, is_sparse=sparse)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            curves[sparse] = [
                float(np.asarray(exe.run(main, feed=batch,
                                         fetch_list=[loss])[0]).ravel()[0])
                for _ in range(5)]
    assert curves[True][-1] < curves[True][0]
    np.testing.assert_allclose(curves[False], curves[True], rtol=2e-3)


def test_merge_and_densify_selected_rows():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[10, 2], is_sparse=True,
                               param_attr=fluid.ParamAttr(name="w_m"))
        loss = layers.mean(layers.reduce_sum(emb, dim=-1))
        optimizer.SGD(learning_rate=0.0).minimize(loss)
    block = main.global_block()
    g = block.var("w_m@GRAD")
    merged = block.create_var(name="merged_g", shape=g.shape, dtype=g.dtype,
                              type="selected_rows", stop_gradient=True)
    block.append_op("merge_selected_rows", {"X": [g.name]},
                    {"Out": [merged.name]})
    dense = block.create_var(name="dense_g", shape=[10, 2], dtype=g.dtype,
                             stop_gradient=True)
    block.append_op("get_tensor_from_selected_rows", {"X": [merged.name]},
                    {"Out": [dense.name]}, {"height": 10})
    exe = fluid.Executor()
    feed = {"ids": np.array([[4, 4, 6]], np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        mv, rows, dv = exe.run(
            main, feed=feed,
            fetch_list=["merged_g", "merged_g@ROWS", "dense_g"])
    mv, rows, dv = np.asarray(mv), np.asarray(rows), np.asarray(dv)
    # d loss / d emb = 1/(B*F)... here mean over [1,3] rows summed last dim
    # -> each lookup position cotangent = 1/3 per element
    assert rows.tolist() == [4, 4, 6]
    np.testing.assert_allclose(mv[0], 2 / 3, rtol=1e-5)   # duplicates summed
    np.testing.assert_allclose(mv[1], 0.0, atol=1e-7)     # zeroed duplicate
    np.testing.assert_allclose(dv[4], 2 / 3, rtol=1e-5)
    np.testing.assert_allclose(dv[6], 1 / 3, rtol=1e-5)
    assert np.abs(dv[[0, 1, 2, 3, 5, 7, 8, 9]]).max() == 0
