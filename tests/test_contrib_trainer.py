"""contrib Trainer/Inferencer (reference contrib/trainer.py:169,
inferencer.py:31): event-driven train loop, test clone, param save,
checkpoint serials with auto-resume, and the infer round trip.
"""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.fluid.contrib import (BeginEpochEvent, BeginStepEvent,
                                      CheckpointConfig, EndEpochEvent,
                                      EndStepEvent, Inferencer, Trainer)

W_TRUE = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)


def _train_func():
    x = layers.data("x", shape=[4])
    y = layers.data("y", shape=[1])
    pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="tr_w"),
                     bias_attr=False, name="pred")
    loss = layers.mean(layers.square_error_cost(pred, y))
    return [loss]


def _infer_func():
    x = layers.data("x", shape=[4])
    return layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="tr_w"),
                     bias_attr=False, name="pred")


def _reader():
    rng = np.random.RandomState(0)
    for _ in range(8):
        xs = rng.rand(16, 4).astype(np.float32)
        ys = xs @ W_TRUE
        yield list(zip(xs, ys))


def test_trainer_events_train_test_infer(tmp_path):
    events = []

    def handler(ev):
        events.append(type(ev).__name__)
        if isinstance(ev, EndStepEvent):
            assert ev.metrics and np.isfinite(
                np.asarray(ev.metrics[0]).item())

    trainer = Trainer(train_func=_train_func,
                      optimizer_func=lambda: optimizer.SGD(0.5))
    trainer.train(num_epochs=6, event_handler=handler, reader=_reader,
                  feed_order=["x", "y"])
    assert events[0] == "BeginEpochEvent" and events[-1] == "EndEpochEvent"
    assert events.count("BeginEpochEvent") == 6
    assert events.count("EndStepEvent") == 48

    # test(): the mean loss after training is small
    (mean_loss,) = trainer.test(reader=_reader, feed_order=["x", "y"])
    assert mean_loss < 0.05, mean_loss

    params_dir = str(tmp_path / "params")
    trainer.save_params(params_dir)
    inf = Inferencer(_infer_func, params_dir)
    xs = np.eye(4, dtype=np.float32)
    (got,) = inf.infer({"x": xs})
    np.testing.assert_allclose(got, W_TRUE, atol=0.2)
    with pytest.raises(ValueError):
        inf.infer([1, 2])

    # save_inference_model exports the served subgraph
    model_dir = str(tmp_path / "inf_model")
    trainer.save_inference_model(model_dir, ["x"], [0])
    assert os.path.exists(os.path.join(model_dir, "__model__"))


def test_trainer_stop_and_fetch_gate():
    seen = []

    def handler(ev):
        if isinstance(ev, BeginStepEvent):
            ev.fetch_metrics = False  # skip fetches entirely
        if isinstance(ev, EndStepEvent):
            seen.append(ev.metrics)
            trainer.stop()  # stop after the first step

    trainer = Trainer(train_func=_train_func,
                      optimizer_func=lambda: optimizer.SGD(0.1))
    trainer.train(num_epochs=5, event_handler=handler, reader=_reader,
                  feed_order=["x", "y"])
    assert len(seen) == 1 and seen[0] == []


def test_checkpoint_config_rejects_degenerate_max(tmp_path):
    """max_num_checkpoints < 1 would make every save retire itself (or
    mis-slice the retire list) — refused up front."""
    import pytest

    for bad in (0, -1):
        with pytest.raises(ValueError, match="max_num_checkpoints"):
            CheckpointConfig(checkpoint_dir=str(tmp_path),
                             max_num_checkpoints=bad)
    # the boundary value keeps exactly the newest serial
    ckpt_dir = str(tmp_path / "one")
    cfg = CheckpointConfig(checkpoint_dir=ckpt_dir, max_num_checkpoints=1,
                           epoch_interval=1, step_interval=1000)
    t = Trainer(train_func=_train_func,
                optimizer_func=lambda: optimizer.SGD(0.1),
                checkpoint_config=cfg)
    t.train(num_epochs=3, event_handler=lambda ev: None, reader=_reader,
            feed_order=["x", "y"])
    assert sorted(os.listdir(ckpt_dir)) == ["checkpoint_2"]


def test_trainer_checkpoint_resume(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = CheckpointConfig(checkpoint_dir=ckpt_dir, max_num_checkpoints=2,
                           epoch_interval=1, step_interval=1000)
    t1 = Trainer(train_func=_train_func,
                 optimizer_func=lambda: optimizer.SGD(0.1),
                 checkpoint_config=cfg)
    t1.train(num_epochs=2, event_handler=lambda ev: None, reader=_reader,
             feed_order=["x", "y"])
    serials = sorted(os.listdir(ckpt_dir))
    assert serials == ["checkpoint_0", "checkpoint_1"]
    with fluid.scope_guard(t1.scope):
        w_trained = np.asarray(t1.scope.find_var("tr_w")).copy()

    # a new trainer with the same config resumes from serial 1
    cfg2 = CheckpointConfig(checkpoint_dir=ckpt_dir, max_num_checkpoints=2)
    t2 = Trainer(train_func=_train_func,
                 optimizer_func=lambda: optimizer.SGD(0.1),
                 checkpoint_config=cfg2)
    assert cfg2.load_serial == 1
    with fluid.scope_guard(t2.scope):
        w_resumed = np.asarray(t2.scope.find_var("tr_w"))
    np.testing.assert_allclose(w_resumed, w_trained, rtol=1e-6)
    # retirement: another epoch pushes serial 2, serial 0 retires
    t2.train(num_epochs=1, event_handler=lambda ev: None, reader=_reader,
             feed_order=["x", "y"])
    serials = sorted(os.listdir(ckpt_dir))
    assert serials == ["checkpoint_1", "checkpoint_2"]
