"""Model zoo smoke + convergence tests on tiny shapes (the reference's
"book"/dist model suite scaled down — SURVEY §4 end-to-end tests)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, optimizer
from paddle_tpu.models import bert, deepfm, resnet, transformer


def _run_steps(main, startup, feed_fn, fetch, n=4):
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        vals = []
        for i in range(n):
            out = exe.run(main, feed=feed_fn(i), fetch_list=fetch)
            vals.append(np.asarray(out[0]))
        return vals


def test_resnet18_train_step():
    main, startup, loss, acc = resnet.build_train_program(
        depth=18, num_classes=10, image_size=32, lr=0.01)
    rng = np.random.RandomState(0)
    imgs = rng.rand(8, 3, 32, 32).astype(np.float32)
    labels = rng.randint(0, 10, (8, 1)).astype(np.int64)
    losses = _run_steps(main, startup,
                        lambda i: {"img": imgs, "label": labels}, [loss], n=6)
    assert all(np.isfinite(l).all() for l in losses)
    assert losses[-1] < losses[0]  # memorizes the fixed batch


@pytest.mark.slow
def test_resnet50_builds_and_runs():
    main, startup, loss, acc = resnet.build_train_program(
        depth=50, num_classes=10, image_size=32)
    rng = np.random.RandomState(0)
    imgs = rng.rand(2, 3, 32, 32).astype(np.float32)
    labels = rng.randint(0, 10, (2, 1)).astype(np.int64)
    losses = _run_steps(main, startup,
                        lambda i: {"img": imgs, "label": labels}, [loss], n=1)
    assert np.isfinite(losses[0]).all()


def test_bert_tiny_mlm_loss_decreases():
    cfg = bert.BertConfig.tiny()
    main, startup, loss = bert.build_pretrain_program(cfg, seq_len=32,
                                                      lr=1e-3)
    batch = bert.synthetic_batch(cfg, 4, 32)
    losses = _run_steps(main, startup, lambda i: batch, [loss], n=6)
    assert all(np.isfinite(l).all() for l in losses)
    assert losses[-1] < losses[0]


def test_deepfm_tiny_train():
    cfg = deepfm.DeepFMConfig.tiny()
    main, startup, loss, pred = deepfm.build_train_program(cfg, lr=1e-2)
    batch = deepfm.synthetic_batch(cfg, 16)
    losses = _run_steps(main, startup, lambda i: batch, [loss], n=8)
    assert all(np.isfinite(l).all() for l in losses)
    assert losses[-1] < losses[0]


def test_transformer_tiny_dygraph_train():
    with dygraph.guard():
        model = transformer.Transformer.tiny()
        opt = optimizer.Adam(learning_rate=1e-3)
        src, tgt, labels, pos = transformer.synthetic_batch(512, 512, 2, 16)
        bias = dygraph.to_variable(transformer.make_causal_bias(16))
        losses = []
        for _ in range(4):
            logits = model(dygraph.to_variable(src), dygraph.to_variable(tgt),
                           dygraph.to_variable(pos), dygraph.to_variable(pos),
                           bias)
            loss = transformer.loss_fn(logits, dygraph.to_variable(labels))
            model.clear_gradients()
            opt.minimize(loss, parameter_list=model.parameters())
            losses.append(float(np.asarray(loss.numpy())))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]


def test_transformer_jit_trace_matches_eager():
    with dygraph.guard():
        model = transformer.Transformer.tiny()
        model.eval()
        src, tgt, labels, pos = transformer.synthetic_batch(512, 512, 2, 16)
        bias = transformer.make_causal_bias(16)
        args = [dygraph.to_variable(v) for v in (src, tgt, pos, pos, bias)]
        eager_out = model(*args).numpy()
        outs, traced = dygraph.jit.trace(model, args)
    static_out = traced([src, tgt, pos, pos, bias])
    np.testing.assert_allclose(np.asarray(static_out[0]), eager_out,
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_resnet_nhwc_matches_nchw():
    """data_format="NHWC" runs the SAME math as NCHW (feed contract
    unchanged — one transpose at graph entry): losses agree to float
    tolerance over steps (reduce orders may differ per layout). On v5e
    the two compile to identical step times (XLA layout assignment
    normalizes; PROFILE_r05.md §2)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(4, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    out = {}
    for fmt in ("NCHW", "NHWC"):
        main, st, loss, acc = resnet.build_train_program(
            depth=18, num_classes=10, image_size=32, seed=3,
            data_format=fmt)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(st)
            out[fmt] = [float(np.asarray(
                exe.run(main, feed=feed, fetch_list=[loss])[0]).ravel()[0])
                for _ in range(3)]
    np.testing.assert_allclose(out["NCHW"], out["NHWC"], rtol=2e-4)
