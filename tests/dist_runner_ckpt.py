"""Kill-resume runner for the fault-tolerance tests: trains a small MLP
single-process with periodic crash-consistent checkpoints, optionally
hard-crashing itself (``faults`` ``worker.exit``) partway through the
FIRST attempt so the parent test can watch ``distributed.launch``
respawn it and ``CheckpointManager.restore_on_restart`` resume it.

Determinism contract: the feed of step ``i`` is derived from
``RandomState(1234 + i)`` and the executor rng is checkpointed, so a
run resumed from any intact checkpoint must reach final weights
BIT-IDENTICAL to an uninterrupted run.

Env knobs (all set by tests/test_fault_tolerance.py):
  PADDLE_CHECKPOINT_DIR   exported by launch(checkpoint_dir=...)
  PADDLE_RESTART_ATTEMPT  set by the launcher (0 first spawn)
  PADDLE_TEST_TOTAL       total training steps (default 12)
  PADDLE_TEST_EVERY       checkpoint every n steps (default 3)
  PADDLE_TEST_KILL_AT     crash after this many completed steps, first
                          attempt only (unset = run to completion)

Prints ``RESUMED <step>`` and ``WEIGHTS <sha256>`` lines the parent
parses from the worker log.
"""

import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import faults, layers, optimizer  # noqa: E402

TOTAL = int(os.environ.get("PADDLE_TEST_TOTAL", "12"))
EVERY = int(os.environ.get("PADDLE_TEST_EVERY", "3"))
KILL_AT = os.environ.get("PADDLE_TEST_KILL_AT")
ATTEMPT = int(os.environ.get("PADDLE_RESTART_ATTEMPT", "0") or 0)


def build(seed=29):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def feed_for(step):
    rs = np.random.RandomState(1234 + step)
    return {"x": rs.rand(4, 6).astype(np.float32),
            "y": rs.rand(4, 1).astype(np.float32)}


def weight_digest(program, scope):
    h = hashlib.sha256()
    for v in sorted(program.list_vars(), key=lambda v: v.name):
        if not v.persistable:
            continue
        val = scope.find_var(v.name)
        if val is not None:
            h.update(v.name.encode())
            h.update(np.ascontiguousarray(np.asarray(val)).tobytes())
    return h.hexdigest()


def main():
    if KILL_AT is not None and ATTEMPT == 0:
        # the crash the gang restart exists for: a hard os._exit after
        # N completed steps (deterministic, counted at the check below)
        faults.arm("worker.exit", after_n=int(KILL_AT))

    main_p, startup, loss = build()
    exe = fluid.Executor()
    exe.run(startup)
    mgr = fluid.io.CheckpointManager(max_to_keep=2)
    resumed = mgr.restore_on_restart(exe, main_p)
    start = resumed if resumed is not None else 0
    print("RESUMED %s" % (resumed if resumed is not None else -1),
          flush=True)
    for step in range(start, TOTAL):
        exe.run(main_p, feed=feed_for(step), fetch_list=[loss],
                checkpoint=(mgr, EVERY))
        faults.check("worker.exit")
    mgr.wait()
    print("WEIGHTS %s" % weight_digest(main_p, fluid.global_scope()),
          flush=True)


if __name__ == "__main__":
    main()
