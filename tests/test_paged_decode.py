"""Paged decode engine: shared KV block pool + per-slot page tables,
prefix caching with copy-on-write page aliasing, and speculative
draft/verify decoding. The load-bearing invariants: every engine emits
tokens BIT-IDENTICAL to the dense ring-cache baseline (ring wraparound
and post-hit COW divergence included), pool exhaustion sheds with the
typed ``Overloaded`` BEFORE any device work, and the speculative tier
costs exactly TWO extra compiles."""

import math

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import monitor
from paddle_tpu.fluid.resilience import Overloaded
from paddle_tpu.models.transformer import (Transformer,
                                           build_decode_session,
                                           build_paged_decode_session,
                                           build_speculative_session)

pytestmark = pytest.mark.decode


def _cm():
    return monitor.counter("executor_compile_cache_miss_total").value


def _drain(paged, out):
    """step() until every slot retires, collecting {slot: tokens}."""
    while paged.active_count:
        for slot, toks, fin in paged.step():
            out[slot] = (np.asarray(toks), bool(fin))
    return out


# -- token identity: paged ≡ dense -----------------------------------------
def test_paged_session_token_identical_to_dense():
    B, S, P, C = 3, 6, 4, 16
    rng = np.random.RandomState(0)
    src = rng.randint(2, 512, (B, S)).astype(np.int64)
    prompt = rng.randint(2, 512, (B, P)).astype(np.int64)
    plens = np.array([4, 3, 2], np.int64)
    with fluid.dygraph.guard():
        model = Transformer.tiny()
        dense = build_decode_session(model, B, S, P, C, end_id=1)
        base, _ = dense.generate(src, prompt, plens, 6)
        paged = build_paged_decode_session(model, B, S, P, C, end_id=1,
                                           page_tokens=4)
        m0 = _cm()
        done = {}
        for b in range(B):
            slot, ready = paged.join(src[b], prompt[b],
                                     prompt_len=int(plens[b]),
                                     max_new_tokens=6)
            assert slot == b          # vacant slots fill in order
            if ready is not None:
                done[slot] = (np.asarray(ready[0]), bool(ready[1]))
        _drain(paged, done)
        m1 = _cm()
    assert m1 - m0 == 2, (
        "paged engine cost %d compiles, want 2 (batch-1 prefill + "
        "paged decode)" % (m1 - m0))
    for b in range(B):
        toks = done[b][0]
        assert np.array_equal(toks, np.asarray(base[b])[:toks.size]), (
            "slot %d: paged tokens diverged from dense" % b)
    # every page went back to the free list at retire
    assert paged.pool.live_pages == 0


def test_paged_ring_wraparound_token_identical():
    """Decode far enough past capacity that every ring position (so
    every page) is overwritten — the `pos % C` write path through the
    table must match the dense ring exactly."""
    B, S, P, C = 1, 6, 4, 8
    rng = np.random.RandomState(1)
    src = rng.randint(2, 512, (B, S)).astype(np.int64)
    prompt = rng.randint(2, 512, (B, P)).astype(np.int64)
    new = 10                  # writes positions 4..13: wraps, covers C
    with fluid.dygraph.guard():
        model = Transformer.tiny()
        dense = build_decode_session(model, B, S, P, C, end_id=1)
        base, _ = dense.generate(src, prompt,
                                 np.array([4], np.int64), new)
        paged = build_paged_decode_session(model, B, S, P, C, end_id=1,
                                           page_tokens=2)
        done = {}
        slot, ready = paged.join(src[0], prompt[0], max_new_tokens=new)
        if ready is not None:
            done[slot] = (np.asarray(ready[0]), bool(ready[1]))
        _drain(paged, done)
    toks = done[0][0]
    assert np.array_equal(toks, np.asarray(base[0])[:toks.size]), (
        "wraparound paged tokens diverged from dense")


# -- prefix caching + copy-on-write ----------------------------------------
def test_prefix_hit_aliases_pages_and_cow_diverges():
    """Second join of the same prompt must HIT (no prefill dispatch),
    alias the cached pages, and still decode the exact dense tokens —
    including past the ring wrap, where BOTH slots copy-on-write the
    shared prompt page before overwriting it."""
    B, S, P, C = 2, 6, 4, 8
    rng = np.random.RandomState(2)
    src = rng.randint(2, 512, (S,)).astype(np.int64)
    prompt = rng.randint(2, 512, (P,)).astype(np.int64)
    with fluid.dygraph.guard():
        model = Transformer.tiny()
        dense = build_decode_session(model, B, S, P, C, end_id=1)
        base, _ = dense.generate(np.stack([src, src]),
                                 np.stack([prompt, prompt]),
                                 np.array([P, P], np.int64), 8)
        paged = build_paged_decode_session(
            model, B, S, P, C, end_id=1, page_tokens=4, pool_pages=8,
            prefix_cache_size=2)
        hit0 = monitor.counter("decode_prefix_hit_total").value
        miss0 = monitor.counter("decode_prefix_miss_total").value
        shared0 = monitor.counter("decode_pages_shared_total").value
        m0 = _cm()
        slot_a, ra = paged.join(src, prompt, max_new_tokens=8)
        slot_b, rb = paged.join(src, prompt, max_new_tokens=8)
        m1 = _cm()
        assert ra is None and rb is None
        assert monitor.counter("decode_prefix_miss_total").value \
            - miss0 == 1
        assert monitor.counter("decode_prefix_hit_total").value \
            - hit0 == 1
        # the hit costs zero compiles and zero prefill dispatches: only
        # the miss's batch-1 prefill compiled
        assert m1 - m0 == 1
        # cache insert + hit alias both bump the share counter
        assert monitor.counter("decode_pages_shared_total").value \
            > shared0
        done = _drain(paged, {})
    for slot in (slot_a, slot_b):
        toks = done[slot][0]
        assert np.array_equal(toks, np.asarray(base[0])[:toks.size]), (
            "slot %d: post-hit tokens diverged from dense" % slot)


# -- admission control ------------------------------------------------------
def test_pool_exhaustion_sheds_typed_overloaded():
    """A pool that cannot seat the prompt must raise ``Overloaded``
    (the serving tier's typed shed signal) at join, BEFORE the prefill
    dispatch, without leaking pages — and admit again once pages
    retire."""
    B, S, P, C = 4, 6, 8, 16
    rng = np.random.RandomState(3)
    src = rng.randint(2, 512, (B, S)).astype(np.int64)
    prompt = rng.randint(2, 512, (B, P)).astype(np.int64)
    with fluid.dygraph.guard():
        model = Transformer.tiny()
        # 2 pages per 8-token prompt at page_tokens=4; 4 usable pages
        # (page 0 is scratch) -> the pool seats TWO prompts while four
        # batch slots sit vacant: pages exhaust first
        paged = build_paged_decode_session(model, B, S, P, C, end_id=1,
                                           page_tokens=4, pool_pages=5)
        for b in range(2):
            _, ready = paged.join(src[b], prompt[b], max_new_tokens=2)
            assert ready is None
        assert paged.pool.free_pages == 0
        steps0 = monitor.counter("decode_steps_total").value
        with pytest.raises(Overloaded):
            paged.join(src[2], prompt[2], max_new_tokens=2)
        # the rejected join ran nothing and allocated nothing
        assert monitor.counter("decode_steps_total").value == steps0
        assert paged.pool.free_pages == 0
        assert paged.pool.live_pages == 4
        done = _drain(paged, {})
        assert len(done) == 2
        # pages are back -> the same request is admitted now
        slot, ready = paged.join(src[2], prompt[2], max_new_tokens=2)
        if ready is None:
            _drain(paged, {})
    assert paged.pool.live_pages == 0


# -- speculative decoding ---------------------------------------------------
def test_speculative_identity_compiles_and_acceptance_ceiling():
    """One dense baseline, two draft configurations: a shallow draft
    must emit bit-identical tokens for exactly two extra compiles and
    never retrace on reuse; a full-depth draft (draft == target) must
    hit the acceptance ceiling — every round accepts all k tokens (the
    histogram mean BENCH_DECODE asserts >= 1.5)."""
    B, S, P, C = 2, 6, 4, 32
    rng = np.random.RandomState(4)
    src = rng.randint(2, 512, (B, S)).astype(np.int64)
    prompt = rng.randint(2, 512, (B, P)).astype(np.int64)
    plens = np.array([4, 3], np.int64)
    with fluid.dygraph.guard():
        model = Transformer.tiny()
        dense = build_decode_session(model, B, S, P, C, end_id=1)
        base, base_fin = dense.generate(src, prompt, plens, 8)
        with pytest.raises(ValueError, match="k"):
            build_speculative_session(model, dense, k=1)
        m0 = _cm()
        spec = build_speculative_session(model, dense, k=3,
                                         draft_layers=1)
        toks, fin = spec.generate(src, prompt, plens, 8)
        m1 = _cm()
        toks2, _ = spec.generate(src, prompt, plens, 8)
        m2 = _cm()
        hist = monitor.get_metric("decode_spec_accepted_tokens")
        c0, s0 = hist.count, hist.sum
        full = build_speculative_session(
            model, dense, k=4, draft_layers=len(model.dec_layers))
        ftoks, _ = full.generate(src, prompt, plens, 8)
    assert m1 - m0 == 2, (
        "speculative tier cost %d compiles, want 2 (draft + verify)"
        % (m1 - m0))
    assert m2 == m1, "speculative generate retraced on reuse"
    assert np.array_equal(toks, base), (
        "speculative tokens diverged from plain greedy decode")
    assert np.array_equal(toks2, base)
    assert np.array_equal(fin, base_fin)
    assert np.array_equal(ftoks, base)
    accepted = (hist.sum - s0) / max(1, hist.count - c0)
    assert accepted == 4.0, (
        "full-depth draft accepted %.2f tokens/step, want the ceiling "
        "k=4" % accepted)


# -- Pallas paged kernel ----------------------------------------------------
def test_paged_kernel_matches_gather_oracle_at_odd_page_counts(
        monkeypatch):
    """Force the Pallas paged tier (interpret mode on CPU) at odd and
    prime pages-per-stream and check it against gather+dense-reference
    — the exact fallback the sessions use below the kernel threshold."""
    from paddle_tpu.kernels import attention as A

    monkeypatch.setenv("PADDLE_TPU_ATTN_FORCE", "paged")
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    B, H, d, ptok = 2, 2, 8, 8
    rng = np.random.RandomState(6)
    for npages in (3, 7, 13):
        C = npages * ptok
        P = B * npages + 1
        k_pool = rng.randn(P, H, ptok, d).astype(np.float32)
        v_pool = rng.randn(P, H, ptok, d).astype(np.float32)
        q = rng.randn(B, H, 1, d).astype(np.float32)
        pages = rng.permutation(np.arange(1, P))[:B * npages]
        table = pages.reshape(B, npages).astype(np.int32)
        lens = np.array([C - 3, (C // 2) + 1], np.int32)
        c0 = monitor.counter("attn_paged_kernel_dispatch_total").value
        got = np.asarray(A.paged_attention_cache(
            q, k_pool, v_pool, table, lens))
        c1 = monitor.counter("attn_paged_kernel_dispatch_total").value
        assert c1 > c0, "forced paged tier fell back (npages=%d)" % npages
        want = np.asarray(A._ref_attention_cache(
            q, A.gather_paged_cache(k_pool, table),
            A.gather_paged_cache(v_pool, table), lens,
            1.0 / math.sqrt(d)))
        np.testing.assert_allclose(got, want, rtol=0, atol=2e-6,
                                   err_msg="npages=%d" % npages)


# -- continuous-batching scatter fusion ------------------------------------
def test_dense_stream_join_is_one_scatter_dispatch():
    """The mid-stream join scatters all 4L per-layer caches in ONE
    fused jitted dispatch — the counter is the regression guard against
    sliding back to 4L separate device calls per join."""
    B, S, P, C = 2, 6, 4, 24
    rng = np.random.RandomState(7)
    src = rng.randint(2, 512, (B, S)).astype(np.int64)
    prompt = rng.randint(2, 512, (B, P)).astype(np.int64)
    with fluid.dygraph.guard():
        model = Transformer.tiny()
        sess = build_decode_session(model, B, S, P, C, end_id=1,
                                    slot_prefill=True)
    st = sess.open_stream()
    c0 = monitor.counter("decode_slot_scatter_dispatch_total").value
    for b in range(B):
        st.join(src[b], prompt[b], max_new_tokens=3)
    c1 = monitor.counter("decode_slot_scatter_dispatch_total").value
    assert c1 - c0 == B, (
        "%d joins dispatched %d cache scatters, want one fused scatter "
        "per join" % (B, c1 - c0))
    while st.active_count:
        st.step()


# -- predictor routing ------------------------------------------------------
def test_generative_predictor_paged_stream_recompiles_flat():
    from paddle_tpu import inference
    from paddle_tpu.models.transformer import PagedDecodeSession

    rng = np.random.RandomState(8)
    src = rng.randint(2, 512, (2, 6)).astype(np.int64)
    prompt = rng.randint(2, 512, (2, 4)).astype(np.int64)
    p = inference.GenerativePredictor(
        Transformer.tiny(), batch_size=2, src_len=6, prompt_len=4,
        cache_capacity=16, end_id=1, paged=True, page_tokens=4,
        prefix_cache_size=2)
    st = p.open_stream()
    assert isinstance(st, PagedDecodeSession)
    with pytest.raises(ValueError, match="open_stream"):
        p.run({"src": src, "prompt": prompt}, max_new_tokens=2)
    rec0 = monitor.counter("predictor_shape_recompile_total").value
    done = {}
    for b in range(2):
        slot, ready = st.join(src[b], prompt[b], max_new_tokens=4)
        if ready is not None:
            done[slot] = ready
    _drain(st, done)
    assert len(done) == 2
    assert monitor.counter("predictor_shape_recompile_total").value \
        == rec0, "paged stream bumped the predictor recompile counter"


# -- geometry validation ----------------------------------------------------
def test_paged_session_validates_geometry():
    with fluid.dygraph.guard():
        model = Transformer.tiny()
        with pytest.raises(ValueError, match="page_tokens"):
            build_paged_decode_session(model, 2, 6, 4, 16, end_id=1,
                                       page_tokens=5)
        with pytest.raises(ValueError, match="pool_pages"):
            build_paged_decode_session(model, 2, 6, 4, 16, end_id=1,
                                       page_tokens=4, pool_pages=3)
