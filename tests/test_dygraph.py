"""DyGraph eager mode: tape autograd, modules, optimizer, jit trace.

Reference analogues: test_imperative_basic.py, test_imperative_mnist.py,
test_imperative_deepcf.py (SURVEY §4)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, optimizer
from paddle_tpu.fluid.dygraph import Layer, nn, to_variable


def test_eager_basic_math_and_backward():
    with dygraph.guard():
        x = to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        x.stop_gradient = False
        y = x * x + x
        loss_var = y._binary(y, "elementwise_mul")  # y*y
        # sum via tracer op
        tracer = fluid.framework._dygraph_tracer()
        (s,) = tracer.trace_op("reduce_sum", {"X": [loss_var]}, ["Out"],
                               {"reduce_all": True, "dim": [0], "keep_dim": False})
        s.backward()
        g = x.gradient()
        # d/dx sum((x^2+x)^2) = 2(x^2+x)(2x+1)
        xv = np.array([[1.0, 2.0], [3.0, 4.0]])
        expected = 2 * (xv * xv + xv) * (2 * xv + 1)
        np.testing.assert_allclose(g, expected, rtol=1e-5)


def test_linear_regression_converges():
    with dygraph.guard():
        model = nn.Linear(4, 1)
        opt = optimizer.SGD(learning_rate=0.1)
        rng = np.random.RandomState(0)
        xv = rng.rand(16, 4).astype(np.float32)
        w_true = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
        yv = xv @ w_true
        losses = []
        for _ in range(60):
            x = to_variable(xv)
            y = to_variable(yv)
            pred = model(x)
            diff = pred - y
            sq = diff * diff
            tracer = fluid.framework._dygraph_tracer()
            (loss,) = tracer.trace_op("mean", {"X": [sq]}, ["Out"], {})
            model.clear_gradients()
            opt.minimize(loss, parameter_list=model.parameters())
            losses.append(float(loss.numpy()))
        # convergence bound: 60 SGD steps must cut the loss by an order
        # of magnitude. The exact rate depends on the init draw (the
        # dygraph param initializer is not seeded by this test's
        # RandomState), so the bound is 10x, not a tight constant —
        # a broken optimizer plateaus far above it.
        assert losses[-1] < losses[0] * 0.10, (losses[0], losses[-1])


class SimpleNet(Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(num_channels=1, num_filters=4, filter_size=3,
                              padding=1, act="relu")
        self.pool = nn.Pool2D(pool_size=2, pool_stride=2, pool_type="max")
        self.fc = nn.FC(size=10, input_dim=4 * 4 * 4)

    def forward(self, x):
        h = self.conv(x)
        h = self.pool(h)
        return self.fc(h)


def test_conv_net_train_step_adam():
    with dygraph.guard():
        model = SimpleNet()
        opt = optimizer.Adam(learning_rate=1e-2)
        rng = np.random.RandomState(1)
        xv = rng.rand(8, 1, 8, 8).astype(np.float32)
        labels = rng.randint(0, 10, (8, 1)).astype(np.int64)
        tracer = fluid.framework._dygraph_tracer()
        losses = []
        for _ in range(20):
            logits = model(to_variable(xv))
            lab = to_variable(labels)
            sm, loss_vec = tracer.trace_op(
                "softmax_with_cross_entropy",
                {"Logits": [logits], "Label": [lab]},
                ["Softmax", "Loss"], {})
            (loss,) = tracer.trace_op("mean", {"X": [loss_vec]}, ["Out"], {})
            model.clear_gradients()
            opt.minimize(loss, parameter_list=model.parameters())
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses


def test_state_dict_roundtrip():
    with dygraph.guard():
        m1 = nn.Linear(3, 2)
        m2 = nn.Linear(3, 2)
        sd = m1.state_dict()
        m2.set_dict(sd)
        for (n1, p1), (n2, p2) in zip(m1.named_parameters(),
                                      m2.named_parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy())


def test_save_load_dygraph(tmp_path):
    with dygraph.guard():
        m = nn.Linear(3, 2)
        path = str(tmp_path / "model")
        dygraph.save_dygraph(m.state_dict(), path)
        sd, _ = dygraph.load_dygraph(path)
        m2 = nn.Linear(3, 2)
        m2.set_dict(sd)
        np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy())


def test_batchnorm_updates_running_stats():
    with dygraph.guard():
        bn = nn.BatchNorm(num_channels=3)
        x = to_variable(np.random.rand(4, 3, 5, 5).astype(np.float32) + 2.0)
        before = bn._mean.numpy().copy()
        bn(x)
        after = bn._mean.numpy()
        assert not np.allclose(before, after)
        # eval mode: stats frozen
        bn.eval()
        before = bn._mean.numpy().copy()
        bn(x)
        np.testing.assert_allclose(before, bn._mean.numpy())


def test_jit_trace_to_program():
    from paddle_tpu.fluid.dygraph import jit

    with dygraph.guard():
        model = nn.Linear(4, 2, act="relu")
        x = to_variable(np.random.rand(3, 4).astype(np.float32))
        out, traced = jit.trace(model, [x])
        # static replay matches eager output
        (static_out,) = traced(x)
        np.testing.assert_allclose(out.numpy(), static_out, rtol=1e-5)
        types = [op.type for op in traced.program.global_block().ops]
        assert "matmul" in types and "relu" in types


def test_no_grad():
    with dygraph.guard():
        x = to_variable(np.ones((2, 2), np.float32))
        x.stop_gradient = False
        with dygraph.no_grad():
            y = x * x
        assert y.stop_gradient or not fluid.framework._dygraph_tracer()._tape


def test_conv3d_transpose_module():
    with dygraph.guard():
        m = nn.Conv3DTranspose(num_channels=2, num_filters=3, filter_size=2,
                               stride=2)
        x = to_variable(np.random.rand(1, 2, 3, 3, 3).astype(np.float32))
        out = m(x)
        assert tuple(out.numpy().shape) == (1, 3, 6, 6, 6)


def test_continuous_value_model_alias():
    from paddle_tpu.fluid import layers
    assert layers.continuous_value_model is layers.cvm


def test_dygraph_lr_decay_objects_match_static():
    """Dygraph LearningRateDecay objects (reference
    dygraph/learning_rate_scheduler.py:27-553) produce the SAME value
    sequence as their static in-graph twins stepped over runs."""
    from paddle_tpu.fluid import layers
    from paddle_tpu.fluid.dygraph import (CosineDecay, ExponentialDecay,
                                          InverseTimeDecay, NaturalExpDecay,
                                          NoamDecay, PiecewiseDecay,
                                          PolynomialDecay)

    cases = [
        # NoamDecay defaults begin=1 (step 0 divides by zero) — its
        # sequence aligns with the static twin's from the 2nd fetch on
        (lambda: layers.noam_decay(64, 4),
         NoamDecay(64, 4), 1),
        (lambda: layers.exponential_decay(0.5, 3, 0.7, staircase=True),
         ExponentialDecay(0.5, 3, 0.7, staircase=True), 0),
        (lambda: layers.natural_exp_decay(0.5, 3, 0.7),
         NaturalExpDecay(0.5, 3, 0.7), 0),
        (lambda: layers.inverse_time_decay(0.5, 3, 0.7),
         InverseTimeDecay(0.5, 3, 0.7), 0),
        (lambda: layers.polynomial_decay(0.5, 4, 0.01, power=2.0, cycle=True),
         PolynomialDecay(0.5, 4, 0.01, power=2.0, cycle=True), 0),
        (lambda: layers.cosine_decay(0.5, 2, 4),
         CosineDecay(0.5, 2, 4), 0),
        (lambda: layers.piecewise_decay([2, 5], [0.3, 0.2, 0.1]),
         PiecewiseDecay([2, 5], [0.3, 0.2, 0.1], begin=0), 0),
    ]
    n_steps = 7
    for build_static, dy, offset in cases:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            lr_var = build_static()
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            static_seq = [float(np.asarray(
                exe.run(main, fetch_list=[lr_var])[0]).reshape(-1)[0])
                for _ in range(n_steps + offset)]
        dy_seq = [dy() for _ in range(n_steps)]
        np.testing.assert_allclose(
            dy_seq, static_seq[offset:], rtol=1e-5,
            err_msg=type(dy).__name__)


def test_dygraph_lr_decay_drives_optimizer():
    """An optimizer constructed with learning_rate=PiecewiseDecay steps
    the schedule once per minimize: update magnitudes drop across the
    boundary, and the object survives a state_dict round trip."""
    from paddle_tpu.fluid.dygraph import PiecewiseDecay

    sched = PiecewiseDecay([2], [0.5, 0.125], begin=0)
    with dygraph.guard():
        p = to_variable(np.zeros((1,), np.float32))
        p.stop_gradient = False
        opt = optimizer.SGD(learning_rate=sched)
        deltas = []
        for _ in range(4):
            before = p.numpy().copy()
            p.clear_gradient()
            loss = p * to_variable(np.ones((1,), np.float32))
            opt.minimize(loss, parameter_list=[p])
            deltas.append(float(np.abs(p.numpy() - before)[0]))
        # steps 0,1 at lr=0.5 (grad 1) then 2,3 at lr=0.125
        np.testing.assert_allclose(deltas, [0.5, 0.5, 0.125, 0.125],
                                   rtol=1e-6)
    st = sched.state_dict()
    sched2 = PiecewiseDecay([2], [0.5, 0.125], begin=0)
    sched2.set_state_dict(st)
    assert sched2.step_num == sched.step_num
    # static-mode misuse fails loudly, pointing at the static twin
    with pytest.raises(TypeError, match="piecewise_decay"):
        float(sched)


def test_optimizer_state_dict_roundtrip_with_lr_decay():
    """Dygraph optimizer.state_dict/set_dict (reference
    optimizer.py:100): Adam moments round-trip by param name through
    save_dygraph/load_dygraph, global_step restores the LR decay
    object, and resumed training matches uninterrupted training."""
    from paddle_tpu.fluid.dygraph import NoamDecay, load_dygraph, \
        save_dygraph

    X = np.random.RandomState(0).rand(16, 4).astype(np.float32)
    Y = (X.sum(1, keepdims=True) * 0.3).astype(np.float32)

    def make():
        # fresh name scope per instantiation, so the checkpoint's
        # name-keyed state matches a rebuilt model (the reference's
        # save/load flow relies on the same deterministic naming)
        with fluid.unique_name.guard():
            model = nn.Linear(4, 1)
        opt = optimizer.AdamOptimizer(
            learning_rate=NoamDecay(d_model=16, warmup_steps=5))
        return model, opt

    def step(model, opt):
        for p in model.parameters():
            p.clear_gradient()
        d = model(to_variable(X)) - to_variable(Y)
        loss = d * d
        tracer = fluid.framework._dygraph_tracer()
        (s,) = tracer.trace_op("reduce_mean", {"X": [loss]}, ["Out"],
                               {"reduce_all": True, "dim": [0],
                                "keep_dim": False})
        opt.minimize(s, parameter_list=model.parameters())

    with dygraph.guard():
        # uninterrupted: 6 steps
        np.random.seed(1)
        m_ref, o_ref = make()
        ref_w0 = [p.numpy().copy() for p in m_ref.parameters()]
        for _ in range(6):
            step(m_ref, o_ref)
        ref = [p.numpy().copy() for p in m_ref.parameters()]

        # interrupted at 3: checkpoint model+opt, restore into FRESH
        # objects, run 3 more
        np.random.seed(1)
        m_a, o_a = make()
        for p, w in zip(m_a.parameters(), ref_w0):
            p._ivar = p._ivar * 0 + w     # same init as the ref run
        for _ in range(3):
            step(m_a, o_a)
        sd_m = m_a.state_dict()
        sd_o = o_a.state_dict()
        assert "global_step" in sd_o and int(
            np.asarray(sd_o["global_step"])[0]) == 4  # begin=1 + 3 steps
        import tempfile

        path = tempfile.mkdtemp() + "/ckpt"
        save_dygraph(sd_m, path)          # -> ckpt.pdparams
        save_dygraph(sd_o, path)          # -> ckpt.pdopt (suffix rule)
        m_b, o_b = make()
        loaded, loaded_opt = load_dygraph(path)
        assert loaded_opt is not None and "global_step" in loaded_opt
        m_b.set_dict(loaded)
        o_b.set_dict(loaded_opt)
        assert o_b._learning_rate.step_num == 4
        # a re-save BEFORE the first step must not lose the restored
        # (still-pending) accumulators
        resaved = o_b.state_dict()
        assert any(k.endswith("@m") for k in resaved), sorted(resaved)
        for _ in range(3):
            step(m_b, o_b)
        got = [p.numpy().copy() for p in m_b.parameters()]
    for a, b in zip(got, ref):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_sequential_and_backward_strategy():
    """dygraph.Sequential (reference container.py:20) chains sublayers
    in order (positional and (name, layer) forms, mutation protocol);
    BackwardStrategy is accepted by backward()."""
    with dygraph.guard():
        m = dygraph.Sequential(nn.Linear(4, 8, act="relu"),
                               nn.Linear(8, 2))
        x = to_variable(np.random.RandomState(0)
                        .rand(3, 4).astype(np.float32))
        out = m(x)
        assert out.numpy().shape == (3, 2)
        assert len(m) == 2 and isinstance(m[0], nn.Linear)
        # named form + replacement
        m2 = dygraph.Sequential(("a", nn.Linear(4, 4)),
                                ("b", nn.Linear(4, 2)))
        m2["b"] = nn.Linear(4, 3)
        assert m2(x).numpy().shape == (3, 3)
        del m2["a"]
        assert len(m2) == 1
        # parameters flow through the container for the optimizer
        assert len(m.parameters()) == 4
        bs = dygraph.BackwardStrategy()
        bs.sort_sum_gradient = True
        x.stop_gradient = False
        y = m(x)
        s = (y * y)._binary(y, "elementwise_mul")
        tracer = fluid.framework._dygraph_tracer()
        (loss,) = tracer.trace_op("reduce_sum", {"X": [s]}, ["Out"],
                                  {"reduce_all": True, "dim": [0],
                                   "keep_dim": False})
        loss.backward(bs)
        assert m.parameters()[0].gradient() is not None
