"""tree_conv op/layer/dygraph module (reference tree_conv_op.cc +
math/tree2col.cc) and the dygraph NCE module."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, layers, optimizer
from paddle_tpu.fluid.dygraph import nn, to_variable

# tree: 1 -> (2, 3), 2 -> (4, 5); padding row
EDGES = np.array([[[1, 2], [1, 3], [2, 4], [2, 5], [0, 0]]], np.int32)
CHILDREN = {1: [2, 3], 2: [4, 5], 3: [], 4: [], 5: []}


def _np_tree_conv(feat, filt, max_depth):
    """DFS reference implementing the tree2col patch semantics."""
    B, N, F = feat.shape
    _, _, K, NF = filt.shape
    out = np.zeros((B, N, K, NF), np.float64)
    for b in range(B):
        for u in range(1, N + 1):
            items = [(u, 1, 1, 0)]
            frontier = [(u, 0)]
            seen = {u}
            while frontier:
                node, depth = frontier.pop(0)
                for i, ch in enumerate(CHILDREN.get(node, [])):
                    if ch not in seen and depth + 1 < max_depth:
                        seen.add(ch)
                        items.append((ch, i + 1, len(CHILDREN[node]),
                                      depth + 1))
                        frontier.append((ch, depth + 1))
            pt = np.zeros(F)
            pl = np.zeros(F)
            pr = np.zeros(F)
            for (v, idx, pclen, depth) in items:
                et = (max_depth - depth) / max_depth
                fr = 0.5 if pclen == 1 else (idx - 1) / (pclen - 1)
                f = feat[b, v - 1]
                pt += et * f
                pl += (1 - et) * fr * f
                pr += (1 - et) * (1 - fr) * f
            out[b, u - 1] = (np.einsum("f,fko->ko", pt, filt[:, 0]) +
                             np.einsum("f,fko->ko", pl, filt[:, 1]) +
                             np.einsum("f,fko->ko", pr, filt[:, 2]))
    return out.astype(np.float32)


def test_tree_conv_matches_dfs_reference():
    rng = np.random.RandomState(0)
    N, F, K, NF, D = 5, 3, 2, 2, 2
    feat = rng.randn(1, N, F).astype(np.float32)
    filt = rng.randn(F, 3, K, NF).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        nv = layers.data("tc_nv", [N, F], dtype="float32")
        es = layers.data("tc_es", [5, 2], dtype="int32")
        out = layers.tree_conv(nv, es, output_size=K, num_filters=NF,
                               max_depth=D, act=None,
                               param_attr=fluid.ParamAttr(name="tc_w"))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.global_scope().set_var("tc_w", filt)
        o, = exe.run(main, feed={"tc_nv": feat, "tc_es": EDGES},
                     fetch_list=[out])
    np.testing.assert_allclose(np.asarray(o), _np_tree_conv(feat, filt, D),
                               rtol=1e-4, atol=1e-5)


def test_tree_conv_deeper_depth():
    rng = np.random.RandomState(1)
    N, F, D = 5, 2, 3
    feat = rng.randn(1, N, F).astype(np.float32)
    filt = rng.randn(F, 3, 1, 1).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        nv = layers.data("tc2_nv", [N, F], dtype="float32")
        es = layers.data("tc2_es", [5, 2], dtype="int32")
        out = layers.tree_conv(nv, es, output_size=1, num_filters=1,
                               max_depth=D, act=None,
                               param_attr=fluid.ParamAttr(name="tc2_w"))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.global_scope().set_var("tc2_w", filt)
        o, = exe.run(main, feed={"tc2_nv": feat, "tc2_es": EDGES},
                     fetch_list=[out])
    np.testing.assert_allclose(np.asarray(o), _np_tree_conv(feat, filt, D),
                               rtol=1e-4, atol=1e-5)


def test_dygraph_tree_conv_module():
    with dygraph.guard():
        m = nn.TreeConv(feature_size=3, output_size=2, num_filters=2,
                        max_depth=2)
        feat = to_variable(np.random.rand(1, 5, 3).astype(np.float32))
        out = m(feat, to_variable(EDGES))
        assert tuple(out.numpy().shape) == (1, 5, 2, 2)
        assert np.isfinite(out.numpy()).all()


def test_dygraph_nce_module_trains():
    rng = np.random.RandomState(0)
    with dygraph.guard():
        m = nn.NCE(num_total_classes=32, dim=8, num_neg_samples=4)
        opt = optimizer.SGD(learning_rate=0.1)
        costs = []
        x = rng.rand(16, 8).astype(np.float32)
        y = rng.randint(0, 32, (16, 1)).astype(np.int64)
        for _ in range(20):
            cost = m(to_variable(x), to_variable(y))
            tracer = fluid.framework._dygraph_tracer()
            (loss,) = tracer.trace_op("mean", {"X": [cost]}, ["Out"], {})
            m.clear_gradients()
            opt.minimize(loss, parameter_list=m.parameters())
            costs.append(float(loss.numpy()))
        assert costs[-1] < costs[0], costs


def test_tree_conv_gradients_flow():
    """tree_conv must be trainable: numeric grad of the filter via the
    autodiff replay vs finite differences."""
    rng = np.random.RandomState(4)
    N, F = 5, 2
    feat = rng.randn(1, N, F).astype(np.float32) * 0.5
    filt0 = rng.randn(F, 3, 1, 1).astype(np.float32) * 0.5

    def loss_at(filt):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            nv = layers.data("tg_nv", [N, F], dtype="float32")
            es = layers.data("tg_es", [5, 2], dtype="int32")
            out = layers.tree_conv(nv, es, output_size=1, num_filters=1,
                                   max_depth=2, act=None, bias_attr=False,
                                   param_attr=fluid.ParamAttr(name="tg_w"))
            loss = layers.reduce_sum(layers.square(out))
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            fluid.global_scope().set_var("tg_w", filt)
            (lv,) = exe.run(main, feed={"tg_nv": feat, "tg_es": EDGES},
                            fetch_list=[loss])
        return float(np.asarray(lv).ravel()[0])

    def grad_at(filt):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            nv = layers.data("tg_nv", [N, F], dtype="float32")
            es = layers.data("tg_es", [5, 2], dtype="int32")
            out = layers.tree_conv(nv, es, output_size=1, num_filters=1,
                                   max_depth=2, act=None, bias_attr=False,
                                   param_attr=fluid.ParamAttr(name="tg_w"))
            loss = layers.reduce_sum(layers.square(out))
            (g,) = fluid.gradients(loss, main.global_block().var("tg_w"))
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            fluid.global_scope().set_var("tg_w", filt)
            (gv,) = exe.run(main, feed={"tg_nv": feat, "tg_es": EDGES},
                            fetch_list=[g])
        return np.asarray(gv)

    g = grad_at(filt0)
    eps = 1e-3
    num = np.zeros_like(filt0)
    for idx in np.ndindex(filt0.shape):
        up = filt0.copy(); up[idx] += eps
        dn = filt0.copy(); dn[idx] -= eps
        num[idx] = (loss_at(up) - loss_at(dn)) / (2 * eps)
    np.testing.assert_allclose(g, num, rtol=2e-2, atol=2e-3)
