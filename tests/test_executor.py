"""Executor: feed/fetch, persistable state, startup init, backward, optimizer
step. Mirrors reference test_executor_and_mul.py / test_optimizer.py."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer


def test_feed_fetch_mul():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[3], dtype="float32")
        y = layers.data(name="y", shape=[3, 2], dtype="float32", append_batch_size=False)
        out = layers.mul(x, y)
    exe = fluid.Executor()
    xv = np.random.rand(5, 3).astype(np.float32)
    yv = np.random.rand(3, 2).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        (res,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[out])
    np.testing.assert_allclose(res, xv @ yv, rtol=1e-5)


def test_startup_then_train_step_sgd():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        opt = optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    xv = rng.rand(8, 4).astype(np.float32)
    yv = (xv @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)).astype(np.float32)

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(50):
            (lv,) = exe.run(main, feed={"x": xv, "label": yv}, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_param_persistence_across_runs():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[2], dtype="float32")
        out = layers.fc(x, size=2, bias_attr=False)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        w_name = main.all_parameters()[0].name
        w0 = np.asarray(fluid.global_scope().find_var(w_name))
        (r1,) = exe.run(main, feed={"x": np.ones((1, 2), np.float32)}, fetch_list=[out])
        (r2,) = exe.run(main, feed={"x": np.ones((1, 2), np.float32)}, fetch_list=[out])
        np.testing.assert_allclose(r1, r2, rtol=1e-6)
        np.testing.assert_allclose(r1.ravel(), w0.sum(axis=0), rtol=1e-5)


def test_backward_grads_match_numeric():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[3], dtype="float32")
        w = layers.create_parameter([3, 1], "float32", name="w")
        out = layers.mul(x, w)
        loss = layers.mean(out)
        grads = fluid.append_backward(loss)
    exe = fluid.Executor()
    xv = np.random.rand(4, 3).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": xv}, fetch_list=["w@GRAD"])
    # d(mean(x@w))/dw = mean over batch of x, per column
    expected = xv.mean(axis=0, keepdims=True).T / 1.0
    np.testing.assert_allclose(g, expected, rtol=1e-5)


def test_gradients_api():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[3], dtype="float32")
        y = layers.reduce_sum(layers.square(x))
        (gx,) = fluid.gradients(y, x)
    exe = fluid.Executor()
    xv = np.random.rand(2, 3).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-5)


def test_rng_stream_advances():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        u = layers.uniform_random([4], min=0.0, max=1.0)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        (a,) = exe.run(main, fetch_list=[u])
        (b,) = exe.run(main, fetch_list=[u])
    assert not np.allclose(a, b)


def test_dropout_train_vs_test():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[100], dtype="float32")
        d = layers.dropout(x, dropout_prob=0.5, dropout_implementation="upscale_in_train")
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor()
    xv = np.ones((2, 100), np.float32)
    with fluid.scope_guard(fluid.Scope()):
        (train_out,) = exe.run(main, feed={"x": xv}, fetch_list=[d])
        (test_out,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[d.name])
    assert (train_out == 0).any()
    np.testing.assert_allclose(test_out, xv)


def test_py_reader_loop_reference_shape():
    """py_reader (reference layers/io.py): start() -> exe.run without
    feed until core.EOFException; the queue-draining step is DISCARDED
    (state identical before/after EOF), reset() re-arms for epoch 2."""
    B, D = 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = layers.py_reader(capacity=8, shapes=[[B, D], [B, 1]],
                                  dtypes=["float32", "float32"])
        x, y = layers.read_file(reader)
        pred = layers.fc(x, 1, name="pyr_fc")
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(0)
    batches = [(rng.rand(B, D).astype(np.float32),
                rng.rand(B, 1).astype(np.float32)) for _ in range(4)]
    reader.decorate_tensor_provider(lambda: iter(batches))
    exe = fluid.Executor()
    scope = fluid.Scope()
    wname = [v.name for v in main.list_vars()
             if v.persistable and ".w_" in v.name][0]
    first_losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(2):
            reader.start()
            steps, losses = 0, []
            while True:
                try:
                    (lv,) = exe.run(main, fetch_list=[loss])
                    losses.append(float(np.asarray(lv).ravel()[0]))
                    steps += 1
                    if steps == len(batches):
                        w_before_eof = np.asarray(
                            scope.find_var(wname)).copy()
                except fluid.core.EOFException:
                    reader.reset()
                    break
            assert steps == len(batches)
            # the EOF (sentinel) step committed nothing
            np.testing.assert_array_equal(
                np.asarray(scope.find_var(wname)), w_before_eof)
            first_losses.append(losses[0])
    # epoch 2 revisits batch 0 with trained weights
    assert first_losses[1] < first_losses[0], first_losses


# -- step-batched execution: exe.run(..., iters=k) ---------------------------

def _sgd_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_iters_trajectory_matches_sequential_runs():
    """iters=k with stacked [k, ...] feeds: the per-step loss trajectory
    and final weights match k sequential exe.run calls at 1e-6."""
    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(7)
    k = 6
    xs = rng.rand(k, 8, 4).astype(np.float32)
    ys = rng.rand(k, 8, 1).astype(np.float32)
    wname = main.all_parameters()[0].name

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        seq = [float(np.asarray(exe.run(
            main, feed={"x": xs[i], "label": ys[i]},
            fetch_list=[loss])[0]).ravel()[0]) for i in range(k)]
        w_seq = np.asarray(fluid.global_scope().find_var(wname))
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (traj,) = exe.run(main, feed={"x": xs, "label": ys},
                          fetch_list=[loss], iters=k)
        w_bat = np.asarray(fluid.global_scope().find_var(wname))
    traj = np.asarray(traj).ravel()
    assert traj.shape == (k,)
    np.testing.assert_allclose(traj, seq, atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(w_bat, w_seq, atol=1e-6, rtol=1e-6)


def test_iters_invariant_feed_and_single_compile():
    """A per-step-shaped feed is loop-invariant (reused each iteration),
    and a k>1 window compiles exactly ONE executable: the first batched
    run is the only compile-cache miss, repeats are hits."""
    from paddle_tpu.fluid import monitor

    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 4).astype(np.float32),
            "label": rng.rand(8, 1).astype(np.float32)}
    hits = monitor.counter("executor_compile_cache_hit_total")
    misses = monitor.counter("executor_compile_cache_miss_total")
    batched = monitor.counter("executor_batched_run_total")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        m0, h0, b0 = misses.value, hits.value, batched.value
        (t1,) = exe.run(main, feed=feed, fetch_list=[loss], iters=4)
        assert (misses.value - m0, hits.value - h0) == (1, 0)
        (t2,) = exe.run(main, feed=feed, fetch_list=[loss], iters=4)
        assert (misses.value - m0, hits.value - h0) == (1, 1)
        assert batched.value - b0 == 2
    t1 = np.asarray(t1).ravel()
    assert t1.shape == (4,)
    # training on the same batch: the trajectory decreases
    assert t1[-1] < t1[0]
    # the second window starts where the first committed
    assert np.asarray(t2).ravel()[0] < t1[-1]


def test_iters_one_is_the_legacy_path():
    """iters=1 routes through the single-step path byte-for-byte: same
    cache entry as a plain run, and the hook payload is unchanged (no
    'iters' key); batched runs add iters to the record."""
    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 4).astype(np.float32),
            "label": rng.rand(8, 1).astype(np.float32)}
    records = []
    fluid.register_run_hook(records.append)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            n_entries = len(exe._cache)
            exe.run(main, feed=feed, fetch_list=[loss])
            assert len(exe._cache) == n_entries + 1
            exe.run(main, feed=feed, fetch_list=[loss], iters=1)
            # same cache entry — no new compile
            assert len(exe._cache) == n_entries + 1
            assert records[-1]["cache_hit"] is True
            assert set(records[-1]) == {"program_id", "fetch_names",
                                        "wall_time", "cache_hit",
                                        "profiler_enabled"}
            exe.run(main, feed=feed, fetch_list=[loss], iters=3)
            assert records[-1]["iters"] == 3
            assert records[-1]["cache_hit"] is False
    finally:
        fluid.unregister_run_hook(records.append)
    # one hook firing per run call, batched or not
    assert len(records) == 4


def test_iters_stacked_feed_shape_validation():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        y = layers.data(name="y", shape=[3, 2], dtype="float32",
                        append_batch_size=False)
        out = layers.reduce_sum(y)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(ValueError, match="per-step shape \\[5, 2\\]"):
            exe.run(main, feed={"y": np.zeros((2, 5, 2), np.float32)},
                    fetch_list=[out], iters=2)
        with pytest.raises(ValueError, match="pass either the per-step "
                                             "shape"):
            exe.run(main, feed={"y": np.zeros((7, 2), np.float32)},
                    fetch_list=[out], iters=2)
        with pytest.raises(ValueError, match="iters must be >= 1"):
            exe.run(main, feed={"y": np.zeros((3, 2), np.float32)},
                    fetch_list=[out], iters=0)


def test_iters_requires_committed_state():
    """A program that creates new persistables mid-step (startup-style)
    cannot keep a fixed scan carry — refused with the remedy."""
    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(RuntimeError, match="loop-invariant state"):
            exe.run(startup, iters=2)


def test_iters_py_reader_drains_exactly_k_batches():
    """py_reader-fed batched runs pull exactly k batches up front (in
    order), and a window the pass cannot fill raises EOF with nothing
    committed."""
    B, D = 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = layers.py_reader(capacity=8, shapes=[[B, D]],
                                  dtypes=["float32"])
        x = layers.read_file(reader)
        m = layers.reduce_mean(x)
    batches = [(np.full((B, D), i, np.float32),) for i in range(5)]
    reader.decorate_tensor_provider(lambda: iter(batches))
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        reader.start()
        (t1,) = exe.run(main, fetch_list=[m], iters=2)
        (t2,) = exe.run(main, fetch_list=[m], iters=2)
        np.testing.assert_allclose(np.asarray(t1).ravel(), [0.0, 1.0])
        np.testing.assert_allclose(np.asarray(t2).ravel(), [2.0, 3.0])
        # one batch left < k=2: EOF, pass over
        with pytest.raises(fluid.core.EOFException):
            exe.run(main, fetch_list=[m], iters=2)
        # reset/start re-arms, same contract as the single-step path
        reader.start()
        (t3,) = exe.run(main, fetch_list=[m], iters=2)
        np.testing.assert_allclose(np.asarray(t3).ravel(), [0.0, 1.0])


def test_iters_gspmd_matches_sequential():
    """iters=k composes with with_data_parallel (GSPMD): trajectory
    matches the sequential CompiledProgram runs."""
    from paddle_tpu.fluid import compiler

    main, startup, loss = _sgd_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(3)
    k = 3
    xs = rng.rand(k, 8, 4).astype(np.float32)
    ys = rng.rand(k, 8, 1).astype(np.float32)
    cp = compiler.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        seq = [float(np.asarray(exe.run(
            cp, feed={"x": xs[i], "label": ys[i]},
            fetch_list=[loss])[0]).ravel()[0]) for i in range(k)]
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (traj,) = exe.run(cp, feed={"x": xs, "label": ys},
                          fetch_list=[loss], iters=k)
    np.testing.assert_allclose(np.asarray(traj).ravel(), seq, atol=1e-6)


def test_save_load_ops_roundtrip(tmp_path):
    """The save/load op pair (reference save_op.cc / load_op.cc): a
    program's save op writes the POST-step value after commit; a second
    program's load op (fluid.layers.load) reads it back as a constant
    of the compiled step."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    path = str(tmp_path / "w.ptc")
    main, st = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, st):
        x = layers.data("slx", [3])
        w = layers.create_parameter(
            [3], "float32",
            default_initializer=fluid.initializer.Constant(2.0))
        y = layers.reduce_sum(layers.elementwise_mul(x, w))
        # append a save op for the PARAM — written after the step runs
        main.current_block().append_op(
            "save", inputs={"X": [w]}, outputs={},
            attrs={"file_path": path})
        fluid.optimizer.SGD(learning_rate=0.1).minimize(y)
    exe = fluid.Executor()
    feed = {"slx": np.ones((1, 3), np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(st)
        exe.run(main, feed=feed, fetch_list=[y])
        expect = np.asarray(fluid.global_scope().find_var(w.name))
    # post-step value: 2.0 - 0.1*1 = 1.9
    np.testing.assert_allclose(expect, np.full(3, 1.9, np.float32))

    main2, st2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, st2):
        t = layers.create_tensor("float32")
        layers.load(t, path)
        out = layers.scale(t, scale=10.0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(st2)
        (r,) = exe.run(main2, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(r), np.full(3, 19.0), rtol=1e-6)
    # missing file fails loudly when the program is lowered (build-time
    # shape inference is best-effort and defers; the run must raise)
    main3, st3 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main3, st3):
        t3 = layers.create_tensor("float32")
        layers.load(t3, str(tmp_path / "absent.ptc"))
        out3 = layers.scale(t3, scale=2.0)
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(Exception, match="does not exist"):
            exe.run(main3, fetch_list=[out3])
