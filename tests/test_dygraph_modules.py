"""Per-module smoke tests for every dygraph nn module (reference
``dygraph/nn.py`` 16-module surface) — shape + finiteness, plus grads
through one representative."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.dygraph import nn, to_variable


def _rand(*shape):
    return to_variable(np.random.RandomState(0).rand(*shape)
                       .astype(np.float32))


def test_conv3d_module():
    with dygraph.guard():
        m = nn.Conv3D(num_channels=2, num_filters=3, filter_size=2)
        out = m(_rand(1, 2, 5, 5, 5))
        assert tuple(out.numpy().shape) == (1, 3, 4, 4, 4)


def test_pool2d_module_avg():
    with dygraph.guard():
        m = nn.Pool2D(pool_size=2, pool_stride=2, pool_type="avg")
        out = m(_rand(2, 3, 8, 8))
        assert tuple(out.numpy().shape) == (2, 3, 4, 4)


def test_batch_norm_module_updates_stats():
    with dygraph.guard():
        m = nn.BatchNorm(num_channels=4)
        x = _rand(8, 4, 3, 3)
        out = m(x)
        assert tuple(out.numpy().shape) == (8, 4, 3, 3)
        assert np.isfinite(out.numpy()).all()


def test_layer_norm_module():
    with dygraph.guard():
        m = nn.LayerNorm(normalized_shape=6)
        out = m(_rand(4, 6))
        np.testing.assert_allclose(out.numpy().mean(axis=-1), 0.0,
                                   atol=1e-5)


def test_group_norm_module():
    with dygraph.guard():
        m = nn.GroupNorm(channels=4, groups=2)
        out = m(_rand(2, 4, 3, 3))
        assert np.isfinite(out.numpy()).all()


def test_prelu_module_modes():
    with dygraph.guard():
        neg = to_variable(-np.ones((2, 3), np.float32))
        out = nn.PRelu(mode="all")(neg)
        np.testing.assert_allclose(out.numpy(), -0.25)
        out = nn.PRelu(mode="channel", channel=3)(neg)
        np.testing.assert_allclose(out.numpy(), -0.25)


def test_bilinear_tensor_product_module():
    with dygraph.guard():
        m = nn.BilinearTensorProduct(input1_dim=3, input2_dim=4,
                                     output_dim=5)
        out = m(_rand(2, 3), _rand(2, 4))
        assert tuple(out.numpy().shape) == (2, 5)


def test_embedding_module():
    with dygraph.guard():
        m = nn.Embedding(size=[10, 4])
        ids = to_variable(np.array([[1], [3]], np.int64))
        out = m(ids)
        assert out.numpy().reshape(2, 4).shape == (2, 4)


def test_gru_unit_module_steps():
    with dygraph.guard():
        H = 4
        m = nn.GRUUnit(size=3 * H)
        x = _rand(2, 3 * H)
        h = _rand(2, H)
        out = m(x, h)
        hidden = out[0] if isinstance(out, (list, tuple)) else out
        assert tuple(hidden.numpy().shape) == (2, H)


def test_spectral_norm_module_normalizes():
    with dygraph.guard():
        w = _rand(6, 4)
        m = nn.SpectralNorm(weight_shape=[6, 4], power_iters=20)
        wn = m(w).numpy()
        # largest singular value ~ 1 after normalization
        s = np.linalg.svd(wn, compute_uv=False)[0]
        assert abs(s - 1.0) < 0.1, s


def test_dropout_module_train_eval():
    with dygraph.guard():
        x = to_variable(np.ones((64, 64), np.float32))
        m = nn.Dropout(p=0.5, dropout_implementation="upscale_in_train")
        train_out = m(x).numpy()
        assert (train_out == 0).any()
        m.eval()
        np.testing.assert_allclose(m(x).numpy(), 1.0)  # upscale: eval = x
        m2 = nn.Dropout(p=0.5)  # downgrade_in_infer: eval = x * keep
        m2.eval()
        np.testing.assert_allclose(m2(x).numpy(), 0.5)


def test_conv2d_transpose_grads_flow():
    with dygraph.guard():
        from paddle_tpu.fluid import optimizer

        m = nn.Conv2DTranspose(num_channels=2, num_filters=2, filter_size=2,
                               stride=2)
        opt = optimizer.SGD(learning_rate=0.1)
        x = _rand(1, 2, 4, 4)
        losses = []
        for _ in range(5):
            out = m(x)
            sq = out * out
            tracer = fluid.framework._dygraph_tracer()
            (loss,) = tracer.trace_op("mean", {"X": [sq]}, ["Out"], {})
            m.clear_gradients()
            opt.minimize(loss, parameter_list=m.parameters())
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


def test_layer_norm_module_eager():
    """Eager layer_norm (the dygraph Transformer path): the lowering's
    declared-dtype stats query must work under _EagerCtx (r5 regression:
    var_dtype missing broke the transformer bench)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.fluid.dygraph import nn, to_variable

    with dygraph.guard():
        m = nn.LayerNorm(normalized_shape=[8])
        x = np.random.RandomState(0).rand(4, 8).astype(np.float32)
        out = m(to_variable(x))
        ref = (x - x.mean(-1, keepdims=True)) / np.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
