"""Benchmark entry: prints ONE JSON line with the headline metric.

Run on real TPU hardware by the driver. Current flagship benchmark:
MNIST LeNet train-step throughput (BASELINE.md config 1); vs_baseline is
null until the reference numbers exist (the reference publishes none —
BASELINE.md)."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def bench_lenet(batch_size=256, warmup=3, iters=20):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import lenet

    main, startup, loss, acc = lenet.build_train_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    imgs = rng.rand(batch_size, 1, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, (batch_size, 1)).astype(np.int64)

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main, feed={"img": imgs, "label": labels}, fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(iters):
            (lv,) = exe.run(main, feed={"img": imgs, "label": labels},
                            fetch_list=[loss])
        elapsed = time.perf_counter() - t0
    images_per_sec = batch_size * iters / elapsed
    return images_per_sec


if __name__ == "__main__":
    ips = bench_lenet()
    print(json.dumps({
        "metric": "mnist_lenet_images_per_sec",
        "value": round(float(ips), 1),
        "unit": "images/sec",
        "vs_baseline": None,
    }))
