"""Benchmark entry: prints ONE JSON line with the headline metric.

Run on real TPU hardware by the driver. Flagship benchmark: BERT-base MLM
pretraining train-step throughput (BASELINE.md config 3 — the reference's
ERNIE/BERT Fleet workload), tokens/sec on one chip. ``vs_baseline`` is null:
the reference publishes no benchmark figures (BASELINE.md).

Auditability (the reference's profiler table / op_tester discipline,
``/root/reference/paddle/fluid/platform/profiler.h:166``):
  * step_time_ms and analytic model FLOPs/step are reported alongside
    tokens/sec, and MFU = achieved FLOP/s / chip peak bf16 FLOP/s.
  * the measurement is validated by doubling iters and requiring stable
    tokens/sec (catches un-timed async work), and by a "checked" pass that
    fetches the loss every step and requires it to be finite and decreasing.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets; jax
# exposes one device per chip, so these are per-chip figures).
_PEAK_BF16 = {
    "tpu v2": 45e12,
    "tpu v3": 123e12,
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5": 459e12,
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,
    "tpu v6e": 918e12,
    "tpu v6": 918e12,
}


def _peak_flops(device):
    """Best-effort peak bf16 FLOP/s for the detected chip. Overridable via
    BENCH_PEAK_FLOPS; unknown kinds fall back to v5e (the BASELINE.md
    hardware) and say so in `peak_source`."""
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env), "env:BENCH_PEAK_FLOPS"
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key in sorted(_PEAK_BF16, key=len, reverse=True):
        if key in kind:
            return _PEAK_BF16[key], "device_kind:%s" % kind
    return 197e12, "assumed v5e (unknown device_kind %r)" % kind


def bert_train_flops_per_step(cfg, batch, seq, n_pred=None):
    """Analytic matmul FLOPs for one BERT MLM training step (fwd+bwd ~= 3x
    fwd; 2*M*N*K per matmul). Embedding gathers and elementwise ignored.
    The MLM head runs on the gathered masked positions (n_pred per
    sequence), like the reference's ERNIE mask_pos head — the vocab
    projection FLOPs scale with n_pred, not seq."""
    h, L, V = cfg.hidden, cfg.n_layers, cfg.vocab_size
    per_layer = 24 * batch * seq * h * h + 4 * batch * seq * seq * h
    rows = batch * (n_pred if n_pred else seq)
    head = 2 * rows * h * h + 2 * rows * h * V
    return 3 * (L * per_layer + head)


def _timed_run(exe, main, batch, loss, iters, jax, use_iters=False):
    if use_iters:
        # step-batched window (exe.run(..., iters=k)): ONE dispatch drives
        # all k steps device-side (lax.scan with donated state), so the
        # window measures compute, not k Python+PJRT round trips — this is
        # what stabilized the host-overhead-bound configs (LeNet swung
        # ±40% run-to-run, DeepFM lost 20% under host contention). The
        # feed is loop-invariant (per-step shape, reused each iteration);
        # the untimed first call compiles the k-step executable (k is part
        # of the compile-cache key). fetch_mode="async" keeps the loss
        # trajectory as a FetchHandle — run() issues no host sync, the
        # window closes on block_until_ready (device done, no transfer),
        # and the finiteness check syncs AFTER timing.
        (h,) = exe.run(main, feed=batch, fetch_list=[loss],
                       iters=iters, fetch_mode="async")
        h.block_until_ready()
        t0 = time.perf_counter()
        (h,) = exe.run(main, feed=batch, fetch_list=[loss],
                       iters=iters, fetch_mode="async")
        h.block_until_ready()
        elapsed = time.perf_counter() - t0
        assert np.isfinite(h.numpy()).all()
        return elapsed
    # drain in-flight work so the window times exactly `iters` steps —
    # with millisecond-scale steps any carried-over dispatch shows up as a
    # fixed cost that fakes better scaling at higher iters
    (lv,) = exe.run(main, feed=batch, fetch_list=[loss], return_numpy=False)
    jax.block_until_ready(lv)
    t0 = time.perf_counter()
    for _ in range(iters):
        # keep the loss as a device future: materializing a scalar across a
        # slow host link would serialize the pipeline (training loops fetch
        # metrics every N steps, not every step)
        (lv,) = exe.run(main, feed=batch, fetch_list=[loss],
                        return_numpy=False)
    jax.block_until_ready(lv)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(np.asarray(lv)).all()
    return elapsed


def _stable_throughput(exe, main, feed, loss, iters, jax, units_per_step,
                       what, use_iters=False):
    """Measurement-validation protocol shared by every bench: time `iters`
    then `2*iters` steps; the rates must agree within [0.7, 1.43) or the
    harness is measuring less than it claims. Returns (rate at 2*iters,
    rate at iters, step seconds from the longer run). ``use_iters`` runs
    each window as one step-batched dispatch (``exe.run(..., iters=k)``)."""
    elapsed = _timed_run(exe, main, feed, loss, iters, jax, use_iters)
    elapsed2 = _timed_run(exe, main, feed, loss, 2 * iters, jax, use_iters)
    r1 = units_per_step * iters / elapsed
    r2 = units_per_step * 2 * iters / elapsed2
    assert 0.7 < r2 / r1 < 1.43, (
        "%s not stable when iters doubles (%.0f vs %.0f): the harness is "
        "measuring less than it claims" % (what, r1, r2))
    return r2, r1, elapsed2 / (2 * iters)


def _profile_table(exe, main, batch, loss, jax, steps=3,
                   out_path="bench_profile.txt"):
    """BENCH_PROFILE=1: trace `steps` steps with jax.profiler, parse the
    XPlane proto, and write a per-op device-time table (reference
    ``platform/profiler.h:166`` per-op tables). Parsing needs the
    xplane proto bundled with tensorflow; degrades to a notice when
    absent."""
    import glob as _glob
    import shutil
    import tempfile
    import collections
    import re as _re

    tracedir = tempfile.mkdtemp(prefix="bench_xplane_")
    try:
        jax.profiler.start_trace(tracedir)
        for _ in range(steps):
            (lv,) = exe.run(main, feed=batch, fetch_list=[loss])
        np.asarray(lv)
        jax.profiler.stop_trace()
        try:
            from tensorflow.tsl.profiler.protobuf import xplane_pb2
        except Exception as e:  # pragma: no cover - env without TF
            with open(out_path, "w") as f:
                f.write("xplane parser unavailable (%s); raw trace kept "
                        "in %s\n" % (e, tracedir))
            return
        files = _glob.glob(tracedir + "/**/*.xplane.pb", recursive=True)
        if not files:
            with open(out_path, "w") as f:
                f.write("no .xplane.pb produced under %s\n" % tracedir)
            return
        xs = xplane_pb2.XSpace()
        with open(files[0], "rb") as f:
            xs.ParseFromString(f.read())
        planes = [p for p in xs.planes if "/device:" in p.name
                  and any(len(ln.events) for ln in p.lines)]
        lines = []
        for plane in planes:
            md = plane.event_metadata
            for ln in plane.lines:
                if ln.name != "XLA Ops":
                    continue
                per_inst = collections.Counter()
                per_family = collections.Counter()
                n_inst = collections.Counter()
                total = 0
                for ev in ln.events:
                    name = md[ev.metadata_id].name
                    inst = name.split(" = ")[0].strip().lstrip("%")
                    fam = _re.sub(r"\.\d+$", "", inst)
                    shape = name.split(" = ")[1].split(" ")[0] \
                        if " = " in name else ""
                    per_inst[(inst, shape)] += ev.duration_ps
                    per_family[fam] += ev.duration_ps
                    n_inst[fam] += 1
                    total += ev.duration_ps
                lines.append("== %s: %.3f ms/step device op time ==" %
                             (plane.name, total / 1e9 / steps))
                lines.append("-- by fusion family --")
                for fam, ps in per_family.most_common(15):
                    lines.append("%10.3f ms/step %5.1f%% n=%-5d %s" % (
                        ps / 1e9 / steps, 100.0 * ps / max(total, 1),
                        n_inst[fam] // steps, fam))
                lines.append("-- top instructions --")
                for (inst, shape), ps in per_inst.most_common(25):
                    lines.append("%10.3f ms/step %5.1f%%  %s  %s" % (
                        ps / 1e9 / steps, 100.0 * ps / max(total, 1),
                        inst, shape[:70]))
        if not lines:
            lines = ["no device plane with an 'XLA Ops' line in the "
                     "trace (CPU/interpret run?)"]
        with open(out_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        print("profile table -> %s" % out_path, file=sys.stderr)
    finally:
        shutil.rmtree(tracedir, ignore_errors=True)


def bench_bert(batch_size=128, seq_len=128, warmup=8, iters=25):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import bert

    import jax

    cfg = bert.BertConfig.base()
    main, startup, loss = bert.build_pretrain_program(cfg, seq_len=seq_len,
                                                      use_amp=True)
    exe = fluid.Executor()
    batch = bert.synthetic_batch(cfg, batch_size, seq_len)
    # pre-stage the batch on device (the DataLoader double-buffer path does
    # this during training; the chip may sit behind a slow host link)
    batch = {k: jax.device_put(v) for k, v in batch.items()}

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        # checked pass: loss must be finite every step and decrease overall
        losses = []
        for _ in range(max(warmup, 4)):  # doubles as compile warmup
            (lv,) = exe.run(main, feed=batch, fetch_list=[loss])
            l = float(np.asarray(lv).ravel()[0])
            assert np.isfinite(l), "non-finite loss in checked pass"
            losses.append(l)
        assert losses[-1] < losses[0], (
            "loss did not decrease in checked pass: %r" % losses)

        tps2, tps, step_s = _stable_throughput(
            exe, main, batch, loss, iters, jax, batch_size * seq_len,
            "bert tokens/sec")
        if os.environ.get("BENCH_PROFILE") == "1":
            _profile_table(exe, main, batch, loss, jax)

    # report the larger (more averaged) run
    step_time_ms = step_s * 1e3
    flops = bert_train_flops_per_step(cfg, batch_size, seq_len,
                                      bert.max_predictions(seq_len))
    dev = jax.devices()[0]
    peak, peak_source = _peak_flops(dev)
    achieved = flops / (step_time_ms / 1e3)
    mfu = achieved / peak
    return {
        "tokens_per_sec": round(tps2, 1),
        "tokens_per_sec_half_iters": round(tps, 1),
        "step_time_ms": round(step_time_ms, 3),
        "model_flops_per_step": flops,
        "achieved_flops_per_sec": round(achieved, 1),
        "peak_flops_per_sec": peak,
        "peak_source": peak_source,
        "mfu": round(mfu, 4),
        "batch_size": batch_size,
        "seq_len": seq_len,
        "loss_decreased": True,
    }


def resnet50_train_flops_per_step(batch, image_size=224):
    """Analytic: ResNet-50 fwd ≈ 4.1 GFLOP per 224² image; train ≈ 3x."""
    per_image = 4.1e9 * (image_size / 224.0) ** 2
    return 3 * batch * per_image


def bench_resnet(batch_size=256, image_size=224, warmup=3, iters=10):
    """BASELINE config 2 (ResNet-50 images/sec/chip); opt-in via
    BENCH_RESNET=1 so the driver's default bench stays one workload.
    Batch 256: the v5e sweep (r5) gives 2435 img/s vs 2373 at 128."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    import jax

    main, startup, loss, acc = resnet.build_train_program(
        image_size=image_size, use_amp=True)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {
        "img": jax.device_put(rng.rand(
            batch_size, 3, image_size, image_size).astype("float32")),
        "label": jax.device_put(rng.randint(
            0, 1000, (batch_size, 1)).astype("int64")),
    }
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(warmup):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.asarray(lv)).all()
        ips, _, step_s = _stable_throughput(
            exe, main, feed, loss, iters, jax, batch_size,
            "resnet images/sec")
        if os.environ.get("BENCH_PROFILE") == "1":
            _profile_table(exe, main, feed, loss, jax,
                           out_path="bench_profile_resnet.txt")
    step_ms = step_s * 1e3
    flops = resnet50_train_flops_per_step(batch_size, image_size)
    peak, peak_source = _peak_flops(jax.devices()[0])
    mfu = flops / (step_ms / 1e3) / peak
    assert mfu <= 1.0, (
        "resnet MFU %.3f > 1: peak table wrong or timing missed work"
        % mfu)
    return {"resnet50_images_per_sec": round(ips, 1),
            "resnet50_step_time_ms": round(step_ms, 3),
            "resnet50_mfu": round(mfu, 4),
            "resnet50_peak_source": peak_source,
            "resnet50_batch_size": batch_size}


def bench_lenet(batch_size=1024, warmup=10, iters=100):
    """BASELINE config 1 (MNIST LeNet images/sec/chip, the first e2e
    milestone); opt-in via BENCH_LENET=1. Steps were host-overhead bound
    (~10 ms, ±40% run-to-run under tunnel jitter — PROFILE_r05 §3), so
    the timed windows run step-batched (exe.run(..., iters=k): one
    dispatch, k device-side steps) and measure compute; the first-step
    XLA conv compile can still take minutes on a tunneled chip."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import lenet

    import jax

    main, startup, loss, acc = lenet.build_train_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"img": jax.device_put(
                rng.rand(batch_size, 1, 28, 28).astype("float32")),
            "label": jax.device_put(
                rng.randint(0, 10, (batch_size, 1)).astype("int64"))}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(warmup):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.asarray(lv)).all()
        ips, _, step_s = _stable_throughput(
            exe, main, feed, loss, iters, jax, batch_size,
            "lenet images/sec", use_iters=True)
    return {"lenet_images_per_sec": round(ips, 1),
            "lenet_step_time_ms": round(step_s * 1e3, 3),
            "lenet_batch_size": batch_size}


def bench_longseq(batch_size=8, seq_len=2048, warmup=3, iters=10,
                  prefix="longseq"):
    """Long-context single-chip BERT (opt-in BENCH_LONGSEQ=1): s=2048
    exercises the Q-tiled long kernels (dispatch tier 2), s=4096 the
    flash split-backward tier (kernels/attention.py)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import bert

    import jax

    cfg = bert.BertConfig.base()  # fresh instance per call
    cfg.max_seq = seq_len
    main, startup, loss = bert.build_pretrain_program(cfg, seq_len=seq_len,
                                                      use_amp=True)
    exe = fluid.Executor()
    batch = {k: jax.device_put(v)
             for k, v in bert.synthetic_batch(cfg, batch_size,
                                              seq_len).items()}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(warmup):
            (lv,) = exe.run(main, feed=batch, fetch_list=[loss])
            assert np.isfinite(np.asarray(lv)).all()
        tps, _, step_s = _stable_throughput(
            exe, main, batch, loss, iters, jax, batch_size * seq_len,
            prefix + " tokens/sec")
    flops = bert_train_flops_per_step(cfg, batch_size, seq_len,
                                      bert.max_predictions(seq_len))
    peak, peak_source = _peak_flops(jax.devices()[0])
    mfu = flops / step_s / peak
    assert mfu <= 1.0, (
        "%s MFU %.3f > 1: peak table wrong or timing missed work"
        % (prefix, mfu))
    return {prefix + "_tokens_per_sec": round(tps, 1),
            prefix + "_step_time_ms": round(step_s * 1e3, 3),
            prefix + "_mfu": round(mfu, 4),
            prefix + "_peak_source": peak_source,
            prefix + "_batch_size": batch_size,
            prefix + "_seq_len": seq_len}


def bench_longctx(shard_counts=(1, 2, 4, 8), budget_mb=64, warmup=2,
                  iters=5):
    """Sequence-parallel long-context tier (opt-in BENCH_LONGCTX=1):
    ring/Ulysses attention over the 'sp' mesh axis
    (kernels/attention.py sequence_parallel_attention).

    Four measurements back the tier's claims:
    1. max trainable S under a fixed per-device activation budget, per
       shard count — per-device ring memory is O(S/n) (each device holds
       its q chunk plus one rotating KV chunk), so max S must rise
       STRICTLY with the shard count (asserted). Sized with the static
       liveness estimator (utils/liveness.py) over the fwd+bwd jaxpr of
       one device's chunk-vs-chunk attention step.
    2. attention tokens/sec at fixed global S over 1->8 shards (actual
       shard_map dispatch; on CPU forwarding the virtual devices share
       cores, so the curve is layout overhead, not speedup — on a real
       ICI ring it is the scaling curve).
    3. recompute (RecomputeOptimizer over the transformer's per-block
       checkpoint vars): peak live bytes with vs without at fixed S —
       must drop — with the loss trajectory unchanged (asserted).
    4. sequence-sharded decode: seq_shards=4 session vs unsharded —
       token streams must be identical (asserted).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph, layers, optimizer
    from paddle_tpu.kernels.attention import sequence_parallel_attention
    from paddle_tpu.models import transformer
    from paddle_tpu.utils import liveness

    H, D, B = 4, 64, 1
    budget = budget_mb * 2 ** 20
    out = {"longctx_budget_mb": budget_mb}

    # -- 1. max trainable S per shard count (liveness-sized) ------------
    def chunk_peak_bytes(s_local):
        """fwd+bwd peak of ONE device's per-hop chunk attention — the
        memory that actually bounds S on a fixed-HBM device."""
        q = jnp.zeros((B, s_local, H * D), jnp.float32)

        def step(q, k, v):
            o = sequence_parallel_attention(q, k, v, H, mesh=None,
                                            causal=True)
            return jnp.sum(o * o)

        closed = jax.make_jaxpr(jax.grad(step, argnums=(0, 1, 2)))(q, q, q)
        return liveness.peak_live_bytes(closed)

    max_s = {}
    for n in shard_counts:
        s = 256
        while chunk_peak_bytes(2 * s // n) <= budget and s < 2 ** 20:
            s *= 2
        max_s[n] = s
        out["longctx_max_trainable_s_%dshard" % n] = s
    ordered = [max_s[n] for n in sorted(shard_counts)]
    assert all(a < b for a, b in zip(ordered, ordered[1:])), (
        "max trainable S not strictly increasing with shard count: %r"
        % max_s)

    # -- 2. tokens/sec at fixed global S over the shard ladder ----------
    S_fix = 2048
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S_fix, H * D).astype(np.float32) * 0.5)
    for n in shard_counts:
        mesh = Mesh(np.array(jax.devices()[:n]).reshape(1, n),
                    ("dp", "sp"))

        def step(q, k, v, mesh=mesh, n=n):
            o = sequence_parallel_attention(
                q, k, v, H, mesh=mesh if n > 1 else None, causal=True,
                strategy="ring" if n > 1 else "auto")
            return jnp.sum(o * o)

        g = jax.jit(jax.grad(step, argnums=(0, 1, 2)))
        for _ in range(warmup):
            jax.block_until_ready(g(q, q, q))
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(g(q, q, q))
        dt = (time.perf_counter() - t0) / iters
        out["longctx_attn_tokens_per_sec_%dshard" % n] = \
            round(B * S_fix / dt, 1)
    out["longctx_attn_seq_len"] = S_fix

    # -- 3. recompute: lower peak, unchanged losses ---------------------
    V, Bm, Sm = 64, 4, 64

    def trace_tiny():
        with dygraph.guard():
            model = transformer.Transformer(
                V, V, d_model=32, n_heads=4, d_inner=64, n_layers=2,
                max_len=Sm, dropout_rate=0.0, seq_parallel=True,
                attn_strategy="ring")
            prng = np.random.RandomState(7)
            for _, p in model.named_parameters():
                p.set_value(prng.uniform(-0.1, 0.1,
                                         p.shape).astype(np.float32))
            src, tgt, labels, pos = transformer.synthetic_batch(
                V, V, Bm, Sm)
            bias = transformer.make_causal_bias(Sm)
            args = [dygraph.to_variable(x)
                    for x in (src, tgt, pos, pos, bias)]
            _, tl = dygraph.jit.trace(model, args)
        return model, tl, (src, tgt, pos, bias, labels)

    def train(model, tl, data, recompute):
        src, tgt, pos, bias, labels = data
        startup = fluid.Program()
        with fluid.program_guard(tl.program, startup):
            logits = tl.program.global_block().var(tl._fetch_names[0])
            label = layers.data("lc_label", [Sm, 1], dtype="int64")
            ce = layers.softmax_with_cross_entropy(
                layers.reshape(logits, [-1, V]),
                layers.reshape(label, [-1, 1]))
            loss = layers.mean(ce)
            opt = optimizer.SGD(learning_rate=0.1)
            if recompute:
                opt = optimizer.RecomputeOptimizer(opt)
                opt._set_checkpoints(model.checkpoint_vars(tl.program))
            opt.minimize(loss)
        tl._materialize_scope()
        exe = fluid.Executor()
        feed = dict(zip(tl._feed_names, (src, tgt, pos, pos, bias)))
        feed["lc_label"] = labels
        losses = []
        with fluid.scope_guard(tl._scope):
            exe.run(startup)
            for _ in range(3):
                (lv,) = exe.run(tl.program, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
        return losses, tl, feed, loss.name

    m0, tl0, data = trace_tiny()
    base, tl0, feed0, l0 = train(m0, tl0, data, False)
    m1, tl1, _ = trace_tiny()
    rec, tl1, feed1, l1 = train(m1, tl1, data, True)
    assert max(abs(a - b) for a, b in zip(base, rec)) < 1e-5, (
        "recompute changed the loss trajectory: %r vs %r" % (base, rec))
    p0 = liveness.program_peak_bytes(tl0.program, feed0, tl0._scope, [l0])
    p1 = liveness.program_peak_bytes(tl1.program, feed1, tl1._scope, [l1])
    assert p1 < p0, "recompute did not lower peak: %d >= %d" % (p1, p0)
    out["longctx_peak_live_mb"] = round(p0 / 2 ** 20, 3)
    out["longctx_peak_live_recompute_mb"] = round(p1 / 2 ** 20, 3)
    out["longctx_recompute_saving_pct"] = round(100 * (1 - p1 / p0), 1)

    # -- 4. sequence-sharded decode identity ----------------------------
    SRC, PROMPT, CAP = 16, 8, 16
    rng = np.random.RandomState(3)
    src = rng.randint(2, V, (2, SRC)).astype(np.int64)
    prompt = rng.randint(2, V, (2, PROMPT)).astype(np.int64)
    plens = np.array([PROMPT, PROMPT - 2], np.int64)

    def gen(seq_shards):
        with dygraph.guard():
            model = transformer.Transformer.tiny(V, V)
            prng = np.random.RandomState(11)
            for _, p in model.named_parameters():
                p.set_value(prng.uniform(-0.3, 0.3,
                                         p.shape).astype(np.float32))
            sess = transformer.build_decode_session(
                model, 2, SRC, PROMPT, CAP, end_id=1,
                seq_shards=seq_shards)
        t0 = time.perf_counter()
        toks, _ = sess.generate(src, prompt, plens, 12)
        return toks, time.perf_counter() - t0

    toks1, t1 = gen(1)
    toks4, t4 = gen(4)
    assert np.array_equal(toks1, toks4), (
        "sequence-sharded decode diverged from the unsharded session")
    out["longctx_decode_identical"] = True
    out["longctx_decode_unsharded_s"] = round(t1, 3)
    out["longctx_decode_4shard_s"] = round(t4, 3)
    return out


def bench_multihost(warmup=3, iters=10, grad_mb=4):
    """Hierarchical-DP scaling curve (opt-in BENCH_MULTIHOST=1, the
    MULTICHIP_r06 shape): simulate H hosts x D devices over the local
    device set for H in 1,2,4 and measure (a) steps/sec of an MLP
    trained under ``HierarchicalGradAllReduce`` on the ("host",
    "device") mesh and (b) the per-phase ici/dcn seconds+bytes of a
    ``CrossHostGradSync`` allreduce over a ``grad_mb``-MB gradient,
    with and without DGC top-k compression of the DCN phase — the
    ici/dcn split and the DGC byte reduction are the two numbers the
    DCN story stands on."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor, optimizer
    from paddle_tpu.fluid.transpiler.collective import (
        HierarchicalGradAllReduce)
    from paddle_tpu.parallel import CrossHostGradSync

    ndev = len(jax.devices())
    out = {"multihost_devices": ndev}
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(64, 64).astype(np.float32),
            "y": rng.rand(64, 1).astype(np.float32)}
    for hosts in (1, 2, 4):
        if ndev % hosts or hosts > ndev:
            continue
        dev_per_host = ndev // hosts
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 11
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[64], dtype="float32")
            y = layers.data("y", shape=[1], dtype="float32")
            h = layers.fc(x, size=256, act="relu")
            p = layers.fc(h, size=1)
            loss = layers.mean(layers.square(p - y))
            optimizer.SGD(0.01).minimize(loss)
        HierarchicalGradAllReduce(nranks=ndev).transpile(startup, main)
        compiled = fluid.CompiledProgram(main).with_explicit_collectives(
            loss_name=loss.name, mesh_axes=("host", "device"),
            mesh_shape={"host": hosts, "device": dev_per_host})
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            for _ in range(warmup):
                exe.run(compiled, feed=feed, fetch_list=[loss])
            t0 = time.perf_counter()
            for _ in range(iters):
                (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss])
            jax.block_until_ready(lv)
            step_s = (time.perf_counter() - t0) / iters
        out["multihost_h%d_steps_per_sec" % hosts] = round(1.0 / step_s, 2)

        # phase-attributed allreduce, dense vs DGC-compressed DCN
        n = grad_mb * (1 << 20) // 4
        grad = rng.rand(hosts, dev_per_host, n).astype(np.float32)
        for tag, ratio in (("dense", None), ("dgc", 0.01)):
            monitor.reset()
            sync = CrossHostGradSync(hosts, dev_per_host, dgc_ratio=ratio)
            for _ in range(warmup):
                sync.allreduce([grad])
            monitor.reset()
            t0 = time.perf_counter()
            for _ in range(iters):
                sync.allreduce([grad])
            total = time.perf_counter() - t0
            dump = monitor.dump_json()
            sec = {e["labels"]["phase"]: e["sum"]
                   for e in dump["crosshost_allreduce_seconds"]}
            byt = {e["labels"]["phase"]: e["value"]
                   for e in dump["crosshost_allreduce_bytes_total"]}
            pre = "multihost_h%d_%s" % (hosts, tag)
            out[pre + "_allreduce_ms"] = round(total / iters * 1e3, 3)
            out[pre + "_ici_seconds"] = round(sec.get("ici", 0.0), 4)
            out[pre + "_dcn_seconds"] = round(sec.get("dcn", 0.0), 4)
            out[pre + "_dcn_bytes_per_step"] = \
                int(byt.get("dcn", 0) // iters)
    return out


def bench_deepfm(batch_size=4096, warmup=20, iters=2000):
    """BASELINE config 4 (DeepFM CTR examples/sec/chip); opt-in via
    BENCH_DEEPFM=1. Embedding-gather dominated — the number that matters
    is examples/sec, not MFU. Steps are ~3.8 ms, so the window is LONG
    (2000 iters ≈ 7.5 s x2): 40-iter windows swung 0.48-0.86M ex/s
    run-to-run; at 2000+ iters repeated runs agree within 0.1%
    (1.0865M vs 1.0854M, r5). The windows run step-batched
    (exe.run(..., iters=k)) so host CPU contention — which cost 20% at
    one dispatch per step (PROFILE_r05 §5) — stays out of the number."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import deepfm

    import jax

    cfg = deepfm.DeepFMConfig()
    main, startup, loss, _auc = deepfm.build_train_program(cfg)
    exe = fluid.Executor()
    feed = {k: jax.device_put(v)
            for k, v in deepfm.synthetic_batch(cfg, batch_size).items()}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(warmup):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.asarray(lv)).all()
        eps, _, step_s = _stable_throughput(
            exe, main, feed, loss, iters, jax, batch_size,
            "deepfm examples/sec", use_iters=True)
    return {"deepfm_examples_per_sec": round(eps, 1),
            "deepfm_step_time_ms": round(step_s * 1e3, 3),
            "deepfm_batch_size": batch_size,
            "deepfm_sparse_dim": cfg.sparse_feature_dim}


def bench_embedding(batch_size=256, steps=30, budget=4096,
                    vocab_multiple=16):
    """Sparse embedding engine bench (opt-in BENCH_EMBED=1): DeepFM
    trains with its big table on a HostEmbeddingTable whose vocabulary is
    ``vocab_multiple``x the simulated HBM-resident budget (>= the 10x
    acceptance bar). Every step draws a fresh id batch, so the residency
    engine admits/evicts continuously and the async prefetch overlap is
    exercised for real. Reports steps/sec with and without prefetch,
    lookup-latency p50/p99 from the monitor histogram, and asserts the
    compile bound: grow()ing the vocabulary mid-run adds ZERO compile
    cache misses."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import embedding
    from paddle_tpu.fluid import monitor
    from paddle_tpu.models import deepfm

    vocab = vocab_multiple * budget
    cfg = deepfm.DeepFMConfig(sparse_feature_dim=vocab, num_fields=8,
                              num_dense=8, embedding_size=16,
                              fc_sizes=(64, 64))
    rng = np.random.RandomState(0)

    def fresh_batch():
        return {
            "sparse_ids": rng.randint(0, vocab, (batch_size, 8))
            .astype(np.int64),
            "dense_x": rng.rand(batch_size, 8).astype(np.float32),
            "label": rng.randint(0, 2, (batch_size, 1)).astype(np.int64),
        }

    embedding.reset_tables()
    table = embedding.HostEmbeddingTable(
        "fm_emb", num_rows=vocab, dim=cfg.embedding_size,
        resident_budget=budget, seed=1)
    main, startup, loss, _ = deepfm.build_train_program(cfg,
                                                        residence="host")
    exe = fluid.Executor()
    misses = monitor.counter("executor_compile_cache_miss_total")
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            for _ in range(3):  # warmup / compile
                (lv,) = exe.run(main, feed=fresh_batch(),
                                fetch_list=[loss])
                assert np.isfinite(np.asarray(lv)).all()

            def timed(n, prefetch):
                feeds = [fresh_batch() for _ in range(n + 1)]
                t0 = time.perf_counter()
                for i in range(n):
                    (lv,) = exe.run(main, feed=feeds[i],
                                    fetch_list=[loss],
                                    return_numpy=False)
                    if prefetch:
                        # stage batch i+1's missing rows while step i's
                        # device compute is still in flight
                        embedding.prefetch(main, feeds[i + 1])
                assert np.isfinite(np.asarray(lv)).all()
                return n / (time.perf_counter() - t0)

            sps_cold = timed(steps, prefetch=False)
            sps = timed(steps, prefetch=True)

            # compile bound: doubling the vocabulary mid-run must not
            # retrace — the step is keyed on the budget, never the vocab
            warm_misses = misses.value
            table.grow(2 * vocab)
            # ids stay inside the original range: DeepFM's tiny
            # first-order device table shares the same id feed and
            # cannot grow (grown-range lookups there are exercised by
            # the dedicated engine test instead)
            for _ in range(3):
                (lv,) = exe.run(main, feed=fresh_batch(),
                                fetch_list=[loss])
                assert np.isfinite(np.asarray(lv)).all()
            assert misses.value == warm_misses, (
                "vocabulary growth retraced the program: %d extra "
                "compiles" % (misses.value - warm_misses))

        lookup_h = monitor.histogram("embedding_lookup_seconds",
                                     labels={"table": "fm_emb"})
        hits = monitor.counter("embedding_prefetch_hit_total",
                               labels={"table": "fm_emb"}).value
        evictions = monitor.counter("embedding_evictions_total",
                                    labels={"table": "fm_emb"}).value
        assert hits > 0, "prefetch never hit — overlap path not exercised"
        assert evictions > 0, "no evictions — budget not under pressure"
        return {
            "embed_deepfm_steps_per_sec": round(sps, 2),
            "embed_deepfm_steps_per_sec_no_prefetch": round(sps_cold, 2),
            "embed_examples_per_sec": round(sps * batch_size, 1),
            "embed_lookup_p50_ms": round(
                1e3 * (lookup_h.quantile(0.5) or 0), 3),
            "embed_lookup_p99_ms": round(
                1e3 * (lookup_h.quantile(0.99) or 0), 3),
            "embed_vocab_rows": table.num_rows,
            "embed_resident_budget": budget,
            "embed_vocab_over_budget": round(table.num_rows / budget, 1),
            "embed_prefetch_hits": hits,
            "embed_evictions": evictions,
            "embed_batch_size": batch_size,
        }
    finally:
        embedding.reset_tables()


def transformer_train_flops_per_step(batch, s, d, di, L, V):
    """Analytic matmul FLOPs for one Transformer train step (fwd+bwd ~3x):
    per layer qkvo projections + attention matmuls + FFN, encoder and
    decoder stacks (decoder adds cross-attention), plus the vocab head.
    (Head count cancels out of the attention matmul FLOPs.)"""
    attn_proj = 4 * 2 * batch * s * d * d
    attn_mm = 4 * batch * s * s * d
    ffn = 2 * 2 * batch * s * d * di
    enc_layer = attn_proj + attn_mm + ffn
    dec_layer = 2 * (attn_proj + attn_mm) + ffn
    head = 2 * batch * s * d * V
    return 3 * (L * enc_layer + L * dec_layer + head)


def bench_transformer(batch_size=32, seq_len=64, warmup=3, iters=10):
    """BASELINE config 5 (Transformer-big, dygraph tracer -> XLA JIT);
    opt-in via BENCH_TRANSFORMER=1. The model runs eagerly once under the
    dygraph tracer, the recorded Program gets a loss + Adam appended, and
    the static step is what's timed — the reference's to-static flow."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph, layers, optimizer
    from paddle_tpu.fluid.contrib import mixed_precision
    from paddle_tpu.models import transformer

    import jax

    V, d, di, L = 32000, 1024, 4096, 6  # Transformer.big (16 heads)
    with dygraph.guard():
        model = transformer.Transformer.big(V, V)
        src, tgt, labels, pos = transformer.synthetic_batch(
            V, V, batch_size, seq_len)
        bias = transformer.make_causal_bias(seq_len)
        args = [dygraph.to_variable(v) for v in (src, tgt, pos, pos, bias)]
        _, traced = dygraph.jit.trace(model, args)

    startup = fluid.Program()
    with fluid.program_guard(traced.program, startup):
        logits = traced.program.global_block().var(traced._fetch_names[0])
        label = layers.data("tfm_label", [seq_len, 1], dtype="int64")
        flat = layers.reshape(logits, [-1, V])
        ce = layers.softmax_with_cross_entropy(
            flat, layers.reshape(label, [-1, 1]))
        loss = layers.mean(ce)
        opt = mixed_precision.decorate(optimizer.Adam(learning_rate=1e-4))
        opt.minimize(loss)

    traced._materialize_scope()
    feed = {n: jax.device_put(v) for n, v in
            zip(traced._feed_names, (src, tgt, pos, pos, bias))}
    feed["tfm_label"] = jax.device_put(labels)
    exe = fluid.Executor()
    from paddle_tpu.fluid.executor import scope_guard

    with scope_guard(traced._scope):
        # params came from the eager trace; optimizer/AMP state initializes
        # through the startup program minimize() populated
        exe.run(startup)
        for _ in range(warmup):
            (lv,) = exe.run(traced.program, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.asarray(lv)).all()
        tps, _, step_s = _stable_throughput(
            exe, traced.program, feed, loss, iters, jax,
            batch_size * seq_len, "transformer tokens/sec")
    step_ms = step_s * 1e3
    flops = transformer_train_flops_per_step(batch_size, seq_len, d, di,
                                             L, V)
    peak, peak_source = _peak_flops(jax.devices()[0])
    mfu = flops / (step_ms / 1e3) / peak
    assert mfu <= 1.0, "transformer MFU %.3f > 1" % mfu
    return {"transformer_big_tokens_per_sec": round(tps, 1),
            "transformer_big_step_time_ms": round(step_ms, 3),
            "transformer_big_mfu": round(mfu, 4),
            "transformer_big_peak_source": peak_source,
            "transformer_big_batch_size": batch_size,
            "transformer_big_seq_len": seq_len}


def _build_tower_pipeline(n_layers, n_stages, trace_batch, seq_len, vocab,
                          d_model=64, n_heads=4, d_inner=128, lr=0.1,
                          num_microbatches=4, seed=7):
    """Trace an EncoderTower LM at per-shard microbatch size, cut it into
    ``n_stages`` uniform segments at encoder-layer boundaries, and wrap
    it with ``with_pipeline``. Returns (traced, startup, loss, compiled,
    feed_fn) where feed_fn(batch_rows, seed) builds a full-batch feed."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph, layers, optimizer
    from paddle_tpu.models import transformer

    import jax

    with dygraph.guard():
        model = transformer.EncoderTower(
            vocab, d_model=d_model, n_heads=n_heads, d_inner=d_inner,
            n_layers=n_layers, max_len=seq_len, dropout_rate=0.0)
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, vocab, size=(trace_batch, seq_len),
                          ).astype("int64")
        pos = np.tile(np.arange(seq_len, dtype="int64"), (trace_batch, 1))
        args = [dygraph.to_variable(v) for v in (ids, pos)]
        _, traced = dygraph.jit.trace(model, args)

    startup = fluid.Program()
    with fluid.program_guard(traced.program, startup):
        blk = traced.program.global_block()
        logits = blk.var(traced._fetch_names[0])
        label = layers.data("tower_lbl", [seq_len, 1], dtype="int64")
        ce = layers.softmax_with_cross_entropy(
            layers.reshape(logits, [-1, vocab]),
            layers.reshape(label, [-1, 1]))
        loss = layers.mean(ce)
        opt = optimizer.SGD(learning_rate=lr)
        if n_stages > 1:
            per = n_layers // n_stages
            cuts = [blk.var(model.last_checkpoints[per * (i + 1) - 1])
                    for i in range(n_stages - 1)]
            opt = optimizer.PipelineOptimizer(opt, cut_list=cuts)
        opt.minimize(loss)
    traced._materialize_scope()

    compiled = fluid.CompiledProgram(traced.program).with_pipeline(
        loss_name=loss.name, places=jax.devices()[:n_stages],
        num_microbatches=num_microbatches)

    def feed_fn(batch_rows, fseed=11):
        frng = np.random.RandomState(fseed)
        fids = frng.randint(0, vocab, size=(batch_rows, seq_len),
                            ).astype("int64")
        fpos = np.tile(np.arange(seq_len, dtype="int64"), (batch_rows, 1))
        flbl = frng.randint(0, vocab, size=(batch_rows, seq_len, 1),
                            ).astype("int64")
        feed = dict(zip(traced._feed_names, (fids, fpos)))
        feed["tower_lbl"] = flbl
        return feed

    return traced, startup, loss, compiled, feed_fn


def bench_pipeline(seq_len=32, vocab=256, layers_per_stage=2, mb_rows=4,
                   warmup=2, iters=8):
    """3D-parallelism bench (opt-in BENCH_PIPELINE=1), CPU-mesh friendly.

    Two measurements:
      * bubble fraction — a fixed 2-stage pipeline timed at two
        microbatch counts (M=4 and M=8). The per-tick time comes from
        the slope (T(M2)-T(M1))/(M2-M1), which cancels the fixed
        per-step overhead; the measured bubble (S-1)*tick/T(M) must
        match the analytic (S-1)/(M+S-1) within 10 points, and the
        ``pipeline_bubble_fraction`` gauge must equal the analytic
        value exactly (it is set from the schedule shape at wrap).
      * weak scaling — 1 -> 2 -> 4 stages with ``layers_per_stage``
        encoder layers per stage (the model grows with the mesh), so
        ideal scaling is flat tokens/sec; reported, not asserted.
    """
    from paddle_tpu.fluid import monitor

    import paddle_tpu.fluid as fluid

    def run_config(n_stages, M):
        traced, startup, loss, compiled, feed_fn = _build_tower_pipeline(
            n_layers=layers_per_stage * n_stages, n_stages=n_stages,
            trace_batch=mb_rows, seq_len=seq_len, vocab=vocab,
            num_microbatches=M)
        B = M * mb_rows
        feed = feed_fn(B)
        exe = fluid.Executor()
        with fluid.scope_guard(traced._scope):
            exe.run(startup)
            for _ in range(warmup):
                (lv,) = exe.run(compiled, feed=feed, fetch_list=[loss])
                assert np.isfinite(np.asarray(lv)).all()
            t0 = time.perf_counter()
            for _ in range(iters):
                exe.run(compiled, feed=feed, fetch_list=[loss])
            dt = (time.perf_counter() - t0) / iters
        gauge = monitor.gauge("pipeline_bubble_fraction").value
        return dt, B * seq_len / dt, gauge

    # -- bubble fraction: same 2-stage model, two microbatch counts ------
    S, M1, M2 = 2, 4, 8
    t1, _, g1 = run_config(S, M1)
    t2, _, g2 = run_config(S, M2)
    tick = (t2 - t1) / ((M2 + S - 1) - (M1 + S - 1))
    analytic1 = (S - 1) / (M1 + S - 1)
    analytic2 = (S - 1) / (M2 + S - 1)
    measured = (S - 1) * tick / t1 if tick > 0 else 0.0
    assert g1 == analytic1 and g2 == analytic2, (
        "pipeline_bubble_fraction gauge %r/%r != analytic %r/%r"
        % (g1, g2, analytic1, analytic2))
    assert abs(measured - analytic1) <= 0.10, (
        "measured bubble %.3f vs analytic %.3f: off by more than 10 "
        "points" % (measured, analytic1))

    # -- weak scaling: layers grow with the stage count ------------------
    weak = {}
    for n_stages in (1, 2, 4):
        _, tps, _ = run_config(n_stages, M=8)
        weak["pipeline_weak_tokens_per_sec_%dstage" % n_stages] = (
            round(tps, 1))

    out = {"pipeline_bubble_analytic": round(analytic1, 4),
           "pipeline_bubble_measured": round(measured, 4),
           "pipeline_bubble_gauge": g1,
           "pipeline_tick_seconds": round(tick, 6),
           "pipeline_microbatches_total":
               monitor.counter("pipeline_microbatches_total").value}
    out.update(weak)
    return out


def bench_transformer_decode(batch_sizes=(1, 64), src_len=128,
                             prompt_len=64, cache_capacity=1024,
                             new_tokens=64):
    """Autoregressive greedy decode through the KV-cache fast path
    (opt-in BENCH_DECODE=1). Per batch size: build a Transformer-big
    decode session (ring capacity 1024 — the Pallas decode-kernel
    regime), time the prefill once and the per-token decode loop
    separately, and report GENERATED tokens/sec. The decode program
    never retraces: after the warmup generation the compile-cache miss
    counter must not move, and the full trajectory costs exactly two
    compiles (prefill + decode) — both asserted here, both visible in
    the JSON's monitor sub-dict (decode_steps_total climbs, misses
    don't)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph, monitor
    from paddle_tpu.models import transformer

    out = {}
    for B in batch_sizes:
        with dygraph.guard():
            model = transformer.Transformer.big()
            m0 = monitor.counter("executor_compile_cache_miss_total").value
            sess = transformer.build_decode_session(
                model, B, src_len, prompt_len, cache_capacity, end_id=1)
            rng = np.random.RandomState(0)
            src = rng.randint(2, 32000, (B, src_len)).astype(np.int64)
            prompt = rng.randint(2, 32000,
                                 (B, prompt_len)).astype(np.int64)
            plens = np.full((B,), prompt_len, np.int64)

            sess.generate(src, prompt, plens, 2)  # compile both programs
            m1 = monitor.counter("executor_compile_cache_miss_total").value
            assert m1 - m0 == 2, (
                "decode session cost %d compiles, want 2 (prefill + "
                "decode)" % (m1 - m0))

            t0 = time.perf_counter()
            sess.generate(src, prompt, plens, 1)  # prefill + argmax only
            t_prefill = time.perf_counter() - t0
            dec_hist = monitor.get_metric("decode_step_seconds")
            disp0 = dec_hist.sum if dec_hist is not None else 0.0
            t0 = time.perf_counter()
            toks, _ = sess.generate(src, prompt, plens, new_tokens)
            t_full = time.perf_counter() - t0
            dec_hist = monitor.get_metric("decode_step_seconds")
            disp1 = dec_hist.sum if dec_hist is not None else 0.0
            t0 = time.perf_counter()
            toks2, _ = sess.generate(src, prompt, plens, 2 * new_tokens)
            t_full2 = time.perf_counter() - t0
            m2 = monitor.counter("executor_compile_cache_miss_total").value
            assert m2 == m1, (
                "decode steps retraced: %d extra compiles" % (m2 - m1))
            assert (toks2[:, :new_tokens] == toks).all(), (
                "decode is not deterministic across generations")

        step_s = (t_full2 - t_full) / (B * new_tokens)  # marginal token
        rate = 1.0 / max(step_s, 1e-12)
        r1 = B * (new_tokens - 1) / max(t_full - t_prefill, 1e-12)
        tag = "_batch%d" % B
        out["transformer_decode_tokens_per_sec" + tag] = round(rate, 1)
        out["transformer_decode_tokens_per_sec_short_window" + tag] = \
            round(r1, 1)
        out["transformer_decode_prefill_ms" + tag] = \
            round(t_prefill * 1e3, 3)
        out["transformer_decode_step_ms" + tag] = \
            round(step_s * B * 1e3, 3)
        out["transformer_decode_compile_misses" + tag] = m1 - m0
        # per-phase breakdown (PROFILE_r06 debt): where a full generation
        # spends its wall clock. Decode dispatch is async, so the device
        # sync cost pools at the host boundary — the final token
        # materialization — not in the per-step dispatch times.
        dispatch_s = max(0.0, disp1 - disp0)
        out["transformer_decode_phases" + tag] = {
            "prefill_ms": round(t_prefill * 1e3, 3),
            "decode_dispatch_ms": round(dispatch_s * 1e3, 3),
            "host_boundary_ms": round(
                max(0.0, t_full - t_prefill - dispatch_s) * 1e3, 3),
        }
    # headline: the throughput-oriented batch (the last one)
    out["transformer_decode_tokens_per_sec"] = \
        out["transformer_decode_tokens_per_sec_batch%d" % batch_sizes[-1]]
    out["transformer_decode_new_tokens"] = new_tokens
    out["transformer_decode_prompt_len"] = prompt_len
    out["transformer_decode_cache_capacity"] = cache_capacity
    # the paged/prefix/speculative engine legs (ROADMAP decode metrics)
    from paddle_tpu.models import transformer as _tf
    out.update(bench_decode_engine(
        _tf.Transformer.big, 32000, width=8, src_len=src_len,
        prompt_len=prompt_len, cache_capacity=cache_capacity,
        page_tokens=cache_capacity // 8))
    return out


def bench_decode_engine(model_fn, vocab, width=8, src_len=128,
                        prompt_len=64, cache_capacity=1024,
                        page_tokens=128, pool_frac=0.375, spec_k=4,
                        spec_new_tokens=12, prefix_joins=6,
                        hbm_budget_gb=32.0):
    """The decode ENGINE legs of BENCH_DECODE — the ROADMAP's missing
    serving metrics:

    * concurrent-streams-per-HBM-budget, paged vs dense, from the
      utils/liveness.py peak-bytes estimator over one decode dispatch
      (feeds + state). The dense stream pays width x capacity ring
      caches whether slots are live or not; the paged pool is sized to
      ``pool_frac`` of that (the continuous-batching regime: admitted
      prompts plus growth headroom), so the same budget seats strictly
      more streams — asserted.
    * prefix-hit prefill tokens/sec on a shared-prefix workload: every
      request carries the same (src, prompt), so after the first join
      the prefill dispatch is skipped and the pages are aliased
      copy-on-write — the hit must beat the miss, asserted, and the
      hits' tokens must match the miss's, asserted.
    * accepted-tokens-per-step for greedy speculative decoding with a
      full-depth self-draft (the acceptance ceiling: proposals always
      match), token-identical to the dense baseline and exactly two
      extra compiles — all asserted."""
    from paddle_tpu.fluid import dygraph, monitor
    from paddle_tpu.models import transformer
    from paddle_tpu.utils.liveness import program_peak_bytes

    out = {}
    rng = np.random.RandomState(7)
    B = width
    with dygraph.guard():
        model = model_fn()
        dense = transformer.build_decode_session(
            model, B, src_len, prompt_len, cache_capacity, end_id=1)
        n_pages = cache_capacity // page_tokens
        pool_pages = max(n_pages + 1, int(B * n_pages * pool_frac) + 1)
        paged = transformer.build_paged_decode_session(
            model, B, src_len, prompt_len, cache_capacity, end_id=1,
            page_tokens=page_tokens, pool_pages=pool_pages,
            prefix_cache_size=8)
        H = model.n_heads
        d = model.d_model // H
        L = dense._L

        # ---- streams per HBM budget (liveness estimator) --------------
        dense_prog = getattr(dense.decode_program, "_program",
                             dense.decode_program)
        dense_feed = dict(zip(dense._decode_feeds, [
            np.zeros((B, 1), np.int32), np.zeros((B, 1), bool),
            np.array([1], np.int32),
            np.full((B,), prompt_len, np.int32),
        ] + [np.zeros((B, H, src_len, d), np.float32)
             for _ in range(2 * L)]
          + [np.zeros((B, H, cache_capacity, d), np.float32)
             for _ in range(2 * L)]))
        dense_peak = program_peak_bytes(dense_prog, dense_feed,
                                       dense.scope,
                                       dense._decode_fetches)
        paged_feed = dict(zip(paged._decode_feeds, [
            np.zeros((B, 1), np.int32), np.zeros((B, 1), bool),
            np.array([1], np.int32), np.ones((B,), np.int32),
            np.zeros((B, n_pages), np.int32),
        ] + [np.zeros((B, H, src_len, d), np.float32)
             for _ in range(2 * L)]
          + [np.zeros((pool_pages, H, page_tokens, d), np.float32)
             for _ in range(2 * L)]))
        paged_peak = program_peak_bytes(paged._decode_traced, paged_feed,
                                        paged.scope,
                                        paged._decode_fetches)
        budget = hbm_budget_gb * float(1 << 30)
        streams_dense = B * budget / max(dense_peak, 1)
        streams_paged = B * budget / max(paged_peak, 1)
        assert streams_paged > streams_dense, (
            "paged decode must seat MORE streams per HBM byte: paged "
            "%.1f vs dense %.1f" % (streams_paged, streams_dense))
        out["decode_hbm_budget_gb"] = hbm_budget_gb
        out["decode_peak_bytes_dense"] = int(dense_peak)
        out["decode_peak_bytes_paged"] = int(paged_peak)
        out["decode_streams_per_hbm_budget_dense"] = round(streams_dense,
                                                           1)
        out["decode_streams_per_hbm_budget_paged"] = round(streams_paged,
                                                           1)
        out["decode_paged_pool_pages"] = pool_pages
        out["decode_page_tokens"] = page_tokens

        # ---- shared-prefix workload ----------------------------------
        src1 = rng.randint(2, vocab, (src_len,)).astype(np.int64)
        pr1 = rng.randint(2, vocab, (prompt_len,)).astype(np.int64)

        def run_one(budget_toks=4):
            t0 = time.perf_counter()
            slot, done = paged.join(src1, pr1,
                                    max_new_tokens=budget_toks)
            t_join = time.perf_counter() - t0
            if done is not None:          # finished at the prefill
                return t_join, np.asarray(done[0])
            toks = None
            while toks is None:
                for s_, toks_, _fin in paged.step():
                    if s_ == slot:
                        toks = toks_
            return t_join, np.asarray(toks)

        t_miss, toks_miss = run_one()
        hit_times, hit_ok = [], True
        for _ in range(prefix_joins - 1):
            t_hit, toks_hit = run_one()
            hit_times.append(t_hit)
            hit_ok = hit_ok and np.array_equal(toks_hit, toks_miss)
        t_hit_mean = sum(hit_times) / len(hit_times)
        assert hit_ok, "prefix-hit tokens diverged from the miss join"
        assert t_hit_mean < t_miss, (
            "prefix hit (%.1f ms) did not amortize the prefill "
            "(%.1f ms)" % (t_hit_mean * 1e3, t_miss * 1e3))
        out["decode_prefix_miss_join_ms"] = round(t_miss * 1e3, 3)
        out["decode_prefix_hit_join_ms"] = round(t_hit_mean * 1e3, 3)
        out["decode_prefix_miss_prefill_tokens_per_sec"] = round(
            prompt_len / t_miss, 1)
        out["decode_prefix_hit_prefill_tokens_per_sec"] = round(
            prompt_len / t_hit_mean, 1)
        out["decode_prefix_hit_speedup"] = round(t_miss / t_hit_mean, 2)

        # ---- speculative: full-depth draft = acceptance ceiling ------
        srcB = rng.randint(2, vocab, (B, src_len)).astype(np.int64)
        prB = rng.randint(2, vocab, (B, prompt_len)).astype(np.int64)
        plensB = np.full((B,), prompt_len, np.int64)
        base_toks, _ = dense.generate(srcB, prB, plensB, spec_new_tokens)
        t0 = time.perf_counter()        # time the WARM baseline pass
        base_toks2, _ = dense.generate(srcB, prB, plensB,
                                       spec_new_tokens)
        t_base = time.perf_counter() - t0
        assert (base_toks2 == base_toks).all()
        hist = monitor.get_metric("decode_spec_accepted_tokens")
        c0, s0 = hist.count, hist.sum
        m0 = monitor.counter("executor_compile_cache_miss_total").value
        spec = transformer.build_speculative_session(
            model, dense, k=spec_k, draft_layers=L)
        spec_toks, _ = spec.generate(srcB, prB, plensB, spec_new_tokens)
        m1 = monitor.counter("executor_compile_cache_miss_total").value
        t0 = time.perf_counter()
        spec_toks2, _ = spec.generate(srcB, prB, plensB, spec_new_tokens)
        t_spec = time.perf_counter() - t0
        m2 = monitor.counter("executor_compile_cache_miss_total").value
        assert m1 - m0 == 2, (
            "speculative session cost %d compiles, want 2 (draft + "
            "verify)" % (m1 - m0))
        assert m2 == m1, "speculative decode retraced on reuse"
        assert (spec_toks == base_toks).all() and \
            (spec_toks2 == base_toks).all(), (
            "speculative decode diverged from the dense baseline")
        accepted = (hist.sum - s0) / max(1, hist.count - c0)
        assert accepted >= 1.5, (
            "greedy speculative accepted %.2f tokens/step, want >= 1.5"
            % accepted)
        out["decode_spec_accepted_tokens_per_step"] = round(accepted, 2)
        out["decode_spec_k"] = spec_k
        out["decode_spec_extra_compiles"] = int(m1 - m0)
        out["decode_spec_tokens_per_sec"] = round(
            B * spec_new_tokens / max(t_spec, 1e-12), 1)
        out["decode_spec_baseline_tokens_per_sec"] = round(
            B * spec_new_tokens / max(t_base, 1e-12), 1)
    return out


def bench_decode_profile(B=4, H=16, d=64, page_tokens=128, n_pages=16,
                         pool_pages=None, iters=20):
    """PROFILE_r06 leg (opt-in BENCH_DECODE_PROFILE=1): per-phase
    timings of the paged decode attention at Pallas-regime geometry
    (capacity = n_pages * page_tokens >= the fused-kernel threshold).

    Phases, timed separately over jitted closures:
    * ``index``: pure page-table indexing — jnp.take of the pool rows
    * ``gather``: index + reshape/transpose to the dense [B, H, C, d]
      layout (everything the fallback path adds before attention)
    * ``softmax_v``: masked online attention over the PRE-gathered
      dense cache (the compute floor)
    * ``paged_kernel``: the fused Pallas paged kernel — table indexing
      via scalar prefetch + gather + softmax*V in one pass (interpret
      mode on CPU; the real kernel on TPU)

    Asserts the profiled path dispatched the Pallas paged kernel
    (attn_paged_kernel_dispatch_total moved) — the profile must never
    silently measure the fallback — and that kernel output matches the
    gather+reference oracle."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.fluid import monitor
    from paddle_tpu.kernels import attention as A

    C = n_pages * page_tokens
    P = int(pool_pages) if pool_pages else B * n_pages + 1
    scale = 1.0 / float(np.sqrt(d))
    rng = np.random.RandomState(3)
    k_pool = jnp.asarray(
        rng.randn(P, H, page_tokens, d).astype(np.float32))
    v_pool = jnp.asarray(
        rng.randn(P, H, page_tokens, d).astype(np.float32))
    q = jnp.asarray(rng.randn(B, H, 1, d).astype(np.float32))
    perm = rng.permutation(np.arange(1, P))[:B * n_pages]
    table = jnp.asarray(perm.reshape(B, n_pages).astype(np.int32))
    lens = jnp.asarray(np.full((B,), C - 7, np.int32))

    def timeit(fn, *args):
        r = fn(*args)
        jax.block_until_ready(r)        # compile outside the window
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*args)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / iters

    index = jax.jit(lambda p, t: jnp.take(p, t.reshape(-1), axis=0))
    t_index = timeit(index, k_pool, table)
    gather = jax.jit(A.gather_paged_cache)
    t_gather = timeit(gather, k_pool, table)
    kd = gather(k_pool, table)
    vd = gather(v_pool, table)
    ref = jax.jit(lambda q_, k_, v_, l_: A._ref_attention_cache(
        q_, k_, v_, l_, scale))
    t_attn = timeit(ref, q, kd, vd, lens)

    c0 = monitor.counter("attn_paged_kernel_dispatch_total").value
    old_force = os.environ.get("PADDLE_TPU_ATTN_FORCE")
    old_interp = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")
    os.environ["PADDLE_TPU_ATTN_FORCE"] = "paged"
    if jax.devices()[0].platform == "cpu":
        os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    try:
        paged = jax.jit(
            lambda q_, kp, vp, t_, l_: A.paged_attention_cache(
                q_, kp, vp, t_, l_, scale=scale))
        # interpret mode emulates the kernel per-grid-cell in python —
        # seconds per call at real geometry; 2 iters bound the leg's
        # wall-clock without losing the (already unindicative) number
        if jax.devices()[0].platform == "cpu":
            iters, save_iters = min(iters, 2), iters
        t_paged = timeit(paged, q, k_pool, v_pool, table, lens)
        if jax.devices()[0].platform == "cpu":
            iters = save_iters
        err = float(jnp.max(jnp.abs(
            paged(q, k_pool, v_pool, table, lens) - ref(q, kd, vd,
                                                        lens))))
    finally:
        for k, v in (("PADDLE_TPU_ATTN_FORCE", old_force),
                     ("PADDLE_TPU_PALLAS_INTERPRET", old_interp)):
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    c1 = monitor.counter("attn_paged_kernel_dispatch_total").value
    assert c1 > c0, (
        "profiled path took the gather-dense fallback, not the Pallas "
        "paged kernel — check PADDLE_TPU_ATTN_FORCE/capacity")
    assert err < 1e-4, "paged kernel diverged from oracle by %g" % err
    interpret = jax.devices()[0].platform == "cpu"
    return {
        "decode_profile_geometry": {
            "batch": B, "heads": H, "d_key": d,
            "page_tokens": page_tokens, "n_pages": n_pages,
            "pool_pages": P, "capacity": C,
        },
        "decode_profile_interpret_mode": interpret,
        "decode_profile_index_us": round(t_index * 1e6, 1),
        "decode_profile_gather_us": round(t_gather * 1e6, 1),
        "decode_profile_softmax_v_us": round(t_attn * 1e6, 1),
        "decode_profile_paged_kernel_us": round(t_paged * 1e6, 1),
        "decode_profile_kernel_max_err": err,
        "decode_profile_kernel_dispatches": int(c1 - c0),
    }


def bench_serve(n_clients=64, per_client=8, max_batch_size=16,
                max_queue_delay_ms=1.0, max_req_rows=4):
    """Closed-loop serving-tier load bench (opt-in BENCH_SERVE=1):
    ``n_clients`` threads submit mixed-size requests through the
    dynamic batcher vs. the same request stream through one serialized
    predictor. Reports req/s for both, mean batch occupancy, p50/p99
    latency from the monitor histograms — and asserts the bucket-ladder
    compile bound: after warm-up the recompile counter NEVER moves, no
    matter how many request sizes the stream mixes."""
    import tempfile
    import threading

    import paddle_tpu.fluid as fluid
    from paddle_tpu import inference
    from paddle_tpu.fluid import layers, monitor
    from paddle_tpu.inference import ServeConfig, Server

    tmp = tempfile.mkdtemp(prefix="bench_serve_")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[32], dtype="float32")
        h = layers.fc(x, size=64, act="relu")
        prob = layers.softmax(layers.fc(h, size=8))
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(tmp, ["x"], [prob], exe,
                                      main_program=main)

    rng = np.random.RandomState(0)
    reqs = [rng.rand(rng.randint(1, max_req_rows + 1), 32)
            .astype(np.float32) for _ in range(n_clients * per_client)]
    total_rows = sum(r.shape[0] for r in reqs)

    # serialized baseline: same stream, one request per dispatch, its
    # own predictor warmed over the same ladder (compiles out of the
    # timed window for both sides)
    base = inference.create_predictor(inference.Config(tmp))
    cfg = ServeConfig(max_batch_size=max_batch_size,
                      max_queue_delay_ms=max_queue_delay_ms,
                      max_queue_depth=4 * n_clients)
    # the serial path sees raw request sizes (no bucketing), so warm
    # every size it will serve — compiles stay out of both timed windows
    for b in sorted(set(cfg.ladder()) | set(range(1, max_req_rows + 1))):
        base.run({"x": np.zeros((b, 32), np.float32)})
    t0 = time.perf_counter()
    for r in reqs:
        base.run({"x": r})
    t_serial = time.perf_counter() - t0

    pred = inference.create_predictor(inference.Config(tmp))
    results = {"errors": []}
    with Server() as srv:
        ladder = srv.register("bench", pred, config=cfg,
                              warmup_feed={"x": reqs[0][:1]})
        assert len(pred._seen_sigs) == len(ladder), (
            "warm-up must pre-compile exactly the ladder")
        recompiles0 = monitor.counter(
            "predictor_shape_recompile_total").value

        def client(cid):
            try:
                for i in range(per_client):
                    r = reqs[cid * per_client + i]
                    out = srv.submit("bench",
                                     {"x": r}).result(timeout=120)
                    assert out[0].shape == (r.shape[0], 8)
            except BaseException as e:  # surfaced after join
                results["errors"].append(e)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        t_served = time.perf_counter() - t0
        assert not results["errors"], results["errors"][:3]
        assert len(pred._seen_sigs) == len(ladder), (
            "mixed-size stream grew the signature set past the ladder")
        assert monitor.counter(
            "predictor_shape_recompile_total").value == recompiles0, (
            "mixed-size stream recompiled after warm-up")

    lbl = {"model": "bench"}
    occ = monitor.get_metric("serving_batch_occupancy", labels=lbl)
    e2e = monitor.get_metric("serving_request_seconds", labels=lbl)
    wait = monitor.get_metric("serving_queue_wait_seconds", labels=lbl)
    n = len(reqs)
    return {
        "serve_requests_per_sec": round(n / t_served, 1),
        "serve_serial_requests_per_sec": round(n / t_serial, 1),
        "serve_speedup_vs_serial": round(t_serial / t_served, 3),
        "serve_rows_per_sec": round(total_rows / t_served, 1),
        "serve_mean_batch_occupancy": round(occ.sum / max(occ.count, 1), 4),
        "serve_batches": monitor.get_metric("serving_batches_total",
                                            labels=lbl).value,
        "serve_requests": n,
        "serve_p50_latency_ms": round(1e3 * (e2e.quantile(0.5) or 0), 3),
        "serve_p99_latency_ms": round(1e3 * (e2e.quantile(0.99) or 0), 3),
        "serve_p99_queue_wait_ms": round(1e3 * (wait.quantile(0.99) or 0),
                                         3),
        "serve_shed": monitor.get_metric("serving_shed_total",
                                         labels=lbl).value,
        "serve_bucket_ladder": ladder,
        "serve_clients": n_clients,
        "serve_max_batch_size": max_batch_size,
    }


def _fleet_model_dir(tmp, prelower=True, batch_sizes=(1, 2, 4, 8)):
    """Export the tiny serving model the fleet benches spawn replicas
    on; ``prelower=True`` AOT-compiles the bucket ladder so replica
    processes cold-start with zero live compiles."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[32], dtype="float32")
        h = layers.fc(x, size=64, act="relu")
        prob = layers.softmax(layers.fc(h, size=8))
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(
            tmp, ["x"], [prob], exe, main_program=main,
            prelower=prelower, prelower_batch_sizes=batch_sizes)
    return tmp


def _fleet_spec(model_dir, delay_ms=2.0, queue_depth=64):
    # breaker_threshold is effectively disabled: the bench wants every
    # over-capacity submit to be a deterministic depth shed, not a
    # breaker-mode fast-reject that depends on shed burstiness
    return {"prefix": "fleet/",
            "models": [{"name": "fc", "model_dir": model_dir,
                        "warmup": {"x": {"shape": [1, 32],
                                         "dtype": "float32"}},
                        "config": {"max_batch_size": 8,
                                   "max_queue_delay_ms": delay_ms,
                                   "max_queue_depth": queue_depth,
                                   "breaker_threshold": 10 ** 6}}]}


def _fleet_closed_loop(router_ep, n_clients, per_client, deadline_ms,
                       max_rows=4, on_request=None):
    """Closed-loop client fleet: ``n_clients`` threads, each with its
    own FleetClient, measuring per-request wall time. Returns
    (ok_in_slo, served, shed, errors, latencies_sec)."""
    import threading

    from paddle_tpu.inference import Overloaded
    from paddle_tpu.serving import FleetClient

    rng = np.random.RandomState(7)
    reqs = [rng.rand(rng.randint(1, max_rows + 1), 32).astype(np.float32)
            for _ in range(n_clients * per_client)]
    state = {"ok_slo": 0, "served": 0, "shed": 0, "errors": [],
             "lat": []}
    mu = threading.Lock()

    def client(cid):
        cli = FleetClient(router_ep)
        try:
            for i in range(per_client):
                r = reqs[cid * per_client + i]
                if on_request is not None:
                    on_request(cid, i)
                t0 = time.perf_counter()
                try:
                    out = cli.submit("fc", {"x": r},
                                     deadline_ms=deadline_ms)
                    dt = time.perf_counter() - t0
                    assert out[0].shape == (r.shape[0], 8)
                    with mu:
                        state["served"] += 1
                        state["lat"].append(dt)
                        if dt <= deadline_ms / 1000.0:
                            state["ok_slo"] += 1
                except Overloaded:
                    with mu:
                        state["shed"] += 1
        except BaseException as e:  # surfaced after join
            with mu:
                state["errors"].append(e)
        finally:
            cli.close()

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    state["wall"] = time.perf_counter() - t0
    return state


def bench_fleet(replica_counts=(1, 2, 4), n_clients=8, per_client=24,
                deadline_ms=500.0, scale_queue_depth=6):
    """``BENCH_FLEET=1``: closed-loop serving-fleet bench. One router +
    subprocess replica fleets of {1, 2, 4} at fixed offered load:
    p50/p99 e2e latency and goodput-under-SLO per size, per-replica
    routed counts proving balance. Each replica's admission bound
    (``max_queue_depth=scale_queue_depth`` rows) is deliberately tight
    enough that a single replica sheds part of the offered load; the
    fleet's capacity is then genuinely the sum of its members, and
    goodput — the fraction of the FIXED offered load answered within
    its deadline — must be monotone non-decreasing 1 -> 4 replicas.
    (The wall-clock rate is reported but not asserted on: on a shared
    machine more processes can coalesce smaller batches and run
    slower per request while still serving strictly MORE of the load
    within SLO.) Then the kill run: SIGKILL one of two replicas
    mid-stream — every request is accounted (served or typed-shed,
    requeues counted), and the supervisor's warm respawn re-registers
    with ZERO live compiles (prelowered ladder + disk hits only)."""
    import json as _json
    import tempfile

    from paddle_tpu.distributed.coordination import (CoordClient,
                                                     CoordServer)
    from paddle_tpu.fluid import monitor
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.supervisor import FleetSupervisor

    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    model_dir = _fleet_model_dir(os.path.join(tmp, "model"))
    # scaling leg: per-replica capacity bound, so replicas add capacity;
    # kill leg: generous depth, so sheds reflect the kill alone
    scale_spec = _fleet_spec(model_dir, queue_depth=scale_queue_depth)
    spec = _fleet_spec(model_dir)
    coord = CoordServer().start()
    addr = "%s:%d" % (coord.host, coord.port)
    dbg = CoordClient(addr)
    out = {"fleet_deadline_ms": deadline_ms, "fleet_clients": n_clients,
           "fleet_requests_per_size": n_clients * per_client}

    def wait_members(n, timeout=240):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(dbg.live_members("fleet/replicas/")) >= n:
                return
            time.sleep(0.2)
        raise TimeoutError("only %d/%d replicas registered"
                           % (len(dbg.live_members("fleet/replicas/")), n))

    try:
        goodputs = []
        total = n_clients * per_client
        for n in replica_counts:
            sup = FleetSupervisor(scale_spec, n, addr,
                                  env={"PADDLE_FLEET_LEASE_TTL": "3.0"},
                                  log_dir=os.path.join(tmp, "logs%d" % n))
            router = Router(coord_addr=addr, refresh_interval=0.1)
            try:
                sup.start()
                wait_members(n)
                router.start()
                st = _fleet_closed_loop(
                    "%s:%d" % (router.host, router.port),
                    n_clients, per_client, deadline_ms)
                assert not st["errors"], st["errors"][:3]
                lat = sorted(st["lat"])
                # goodput-under-SLO: fraction of the fixed offered load
                # answered within its deadline — the quantity that is
                # monotone in fleet capacity
                goodput = st["ok_slo"] / total
                goodputs.append(goodput)
                per_rep = {
                    rid: monitor.counter("fleet_replica_routed_total",
                                         labels={"replica": rid}).value
                    for rid in sup.replica_ids()}
                out["fleet_%dx_goodput" % n] = round(goodput, 3)
                out["fleet_%dx_rate_rps" % n] = round(
                    st["served"] / st["wall"], 1)
                out["fleet_%dx_p50_ms" % n] = round(
                    1e3 * lat[len(lat) // 2], 3) if lat else None
                out["fleet_%dx_p99_ms" % n] = round(
                    1e3 * lat[int(len(lat) * 0.99) - 1], 3) if lat else None
                out["fleet_%dx_served" % n] = st["served"]
                out["fleet_%dx_shed" % n] = st["shed"]
                out["fleet_%dx_per_replica" % n] = per_rep
                if n > 1:
                    assert all(v > 0 for v in per_rep.values()), (
                        "unbalanced fleet: %s" % per_rep)
            finally:
                router.close()
                sup.stop(timeout=60)
        # the load must actually saturate ONE replica, else "more
        # replicas do not hurt" would be vacuously true
        assert goodputs[0] < 1.0, (
            "offered load never exceeded a single replica's admission "
            "bound; tighten scale_queue_depth or raise n_clients")
        assert all(b >= a - 0.02 for a, b in zip(goodputs, goodputs[1:])), (
            "goodput-under-SLO regressed with more replicas: %s"
            % [round(g, 3) for g in goodputs])

        # -- kill-one-replica: zero loss, warm respawn ------------------
        sup = FleetSupervisor(spec, 2, addr,
                              env={"PADDLE_FLEET_LEASE_TTL": "3.0"},
                              log_dir=os.path.join(tmp, "logs_kill"))
        router = Router(coord_addr=addr, refresh_interval=0.1)
        try:
            sup.start()
            wait_members(2)
            router.start()
            requeued0 = monitor.counter("fleet_requeued_total").value
            shed0 = monitor.sum_labeled("fleet_shed_total")
            victim = sup.replica_ids()[0]
            pid0 = sup.pid(victim)
            killed = {"done": False}

            def killer(cid, i):
                # first client, a third of the way in: pull the plug
                if cid == 0 and i == per_client // 3 \
                        and not killed["done"]:
                    killed["done"] = True
                    sup.kill(victim)

            st = _fleet_closed_loop(
                "%s:%d" % (router.host, router.port),
                n_clients, per_client, deadline_ms, on_request=killer)
            assert not st["errors"], st["errors"][:3]
            total = n_clients * per_client
            assert st["served"] + st["shed"] == total, (
                "lost requests: %d served + %d shed != %d"
                % (st["served"], st["shed"], total))
            out["fleet_kill_served"] = st["served"]
            out["fleet_kill_shed"] = (
                monitor.sum_labeled("fleet_shed_total") - shed0)
            out["fleet_kill_requeued"] = (
                monitor.counter("fleet_requeued_total").value - requeued0)
            # the supervisor respawned the victim warm: same id, new
            # pid, ZERO live compiles (prelowered ladder off disk)
            deadline = time.time() + 240
            info = None
            while time.time() < deadline:
                blob = dbg.get("fleet/replicas/%s" % victim)
                if blob is not None:
                    info = _json.loads(blob.decode())
                    if info["pid"] != pid0:
                        break
                time.sleep(0.2)
            assert info is not None and info["pid"] != pid0, (
                "victim %s never respawned" % victim)
            assert info["live_compiles"] == 0, info
            out["fleet_respawn_live_compiles"] = info["live_compiles"]
            out["fleet_respawn_warmup_disk_hits"] = \
                info["warmup_disk_hits"]
            out["fleet_respawns"] = sup.respawns
        finally:
            router.close()
            sup.stop(timeout=60)
    finally:
        dbg.close()
        coord.stop()
    return out


def bench_coord_recovery(smoke=False, n_clients=None, per_client=None,
                         deadline_ms=10000.0, model_dir=None):
    """``BENCH_COORD=1``: kill the coordination service mid-run —
    ``CoordServer.crash()``, the in-process equivalent of kill -9: no
    drain, no final snapshot, every connection severed — and restart it
    on the SAME port against the SAME WAL dir while a closed-loop
    client fleet keeps hammering a 2-replica serving fleet. The data
    path never touches the coordinator, so the run must lose ZERO
    requests; the control path degrades visibly and recovers:

      * the router detects the outage (its fail-fast coordination
        client) and keeps routing over the last-known replica set —
        the chaos thread holds the outage open until
        ``fleet_stale_routing_total`` proves requests rode the stale
        view;
      * the restarted server replays its WAL (replica leases included,
        as wall-clock deadlines) at a bumped epoch; replica clients
        re-dial transparently, replay their leases, re-register;
      * the router's next successful refresh clears the stale flag.

    Reported: the outage window (crash -> restarted), the stale-routing
    window (first stale-routed request -> router fresh again), full
    recovery time (crash -> fresh), stale-routed count (must be > 0)
    and requests lost (must be 0)."""
    import tempfile
    import threading

    from paddle_tpu.distributed.coordination import (CoordClient,
                                                     CoordServer)
    from paddle_tpu.fluid import monitor
    from paddle_tpu.serving import Replica, Router

    if n_clients is None:
        n_clients = 2 if smoke else 6
    if per_client is None:
        per_client = 40 if smoke else 48
    # pacing keeps the closed loop alive well past the router's ~1 s
    # outage-detection latency (its coordination client's fail-fast
    # grace), so stale routing is actually exercised, not raced
    pace_s = 0.07 if smoke else 0.05
    tmp = tempfile.mkdtemp(prefix="bench_coord_")
    if model_dir is None:
        model_dir = _fleet_model_dir(os.path.join(tmp, "model"),
                                     prelower=False)
    wal_dir = os.path.join(tmp, "wal")
    spec = _fleet_spec(model_dir)
    coord = CoordServer(wal_dir=wal_dir).start()
    addr = "%s:%d" % (coord.host, coord.port)
    port = coord.port
    epoch0 = coord.epoch
    state = {"coord": coord}
    reps = []
    router = None
    dbg = CoordClient(addr)
    stale0 = monitor.counter("fleet_stale_routing_total").value
    try:
        reps = [Replica(spec, coord_addr=addr,
                        replica_id="cr%d" % i, lease_ttl=5.0,
                        stats_interval=0.1).start()
                for i in range(2)]
        deadline = time.time() + 240
        while len(dbg.live_members("fleet/replicas/")) < 2:
            if time.time() > deadline:
                raise TimeoutError("replicas never registered")
            time.sleep(0.1)
        router = Router(coord_addr=addr, refresh_interval=0.1).start()
        kill_ev = threading.Event()
        marks = {}

        def chaos():
            kill_ev.wait(120)
            marks["t_kill"] = time.perf_counter()
            state["coord"].crash()
            # hold the outage open until the router provably routed
            # over its stale table (bounded: the closed loop outlasts
            # this by construction, but a wedge must not hang forever)
            hold = time.time() + 30
            while time.time() < hold:
                if monitor.counter(
                        "fleet_stale_routing_total").value > stale0:
                    break
                time.sleep(0.02)
            marks["t_stale"] = time.perf_counter()
            state["coord"] = CoordServer(port=port,
                                         wal_dir=wal_dir).start()
            marks["t_up"] = time.perf_counter()
            hold = time.time() + 60
            while time.time() < hold:
                with router._table_mu:
                    fresh = router._stale_since is None
                if fresh and router.members():
                    marks["t_fresh"] = time.perf_counter()
                    return
                time.sleep(0.02)

        ct = threading.Thread(target=chaos, daemon=True)
        ct.start()

        def pacer(cid, i):
            time.sleep(pace_s)
            if cid == 0 and i == per_client // 3:
                kill_ev.set()

        st = _fleet_closed_loop(
            "%s:%d" % (router.host, router.port),
            n_clients, per_client, deadline_ms, on_request=pacer)
        ct.join(120)
        assert not st["errors"], st["errors"][:3]
        total = n_clients * per_client
        lost = total - st["served"] - st["shed"]
        assert lost == 0, (
            "lost requests across the coordinator outage: %d served + "
            "%d shed != %d" % (st["served"], st["shed"], total))
        stale_routed = monitor.counter(
            "fleet_stale_routing_total").value - stale0
        assert stale_routed > 0, (
            "no request ever rode the stale routing table — the outage "
            "never overlapped the load")
        assert "t_fresh" in marks, (
            "router never returned to a fresh view: %s" % marks)
        epoch1 = state["coord"].epoch
        assert epoch1 == epoch0 + 1, (epoch0, epoch1)
        return {
            "coord_requests_total": total,
            "coord_requests_served": st["served"],
            "coord_requests_shed": st["shed"],
            "coord_requests_lost": lost,
            "coord_stale_routed": int(stale_routed),
            "coord_outage_s": round(marks["t_up"] - marks["t_kill"], 3),
            "coord_stale_window_s": round(
                marks["t_fresh"] - marks["t_stale"], 3),
            "coord_recovery_s": round(
                marks["t_fresh"] - marks["t_kill"], 3),
            "coord_epochs": [epoch0, epoch1],
        }
    finally:
        if router is not None:
            router.close()
        for r in reps:
            r.drain(timeout=10)
        dbg.close()
        state["coord"].stop()


def bench_restart():
    """``BENCH_RESTART=1``: restart-to-first-step and serving
    ``register()`` warm-up, cold (empty persistent compile cache) vs
    warm (populated) — the two downtime windows the on-disk AOT tier
    (fluid/compile_cache.py) exists to shrink. Each "restart" is a
    fresh Executor + a rebuilt program (``unique_name.guard`` makes the
    rebuild byte-identical, as a real process restart would be), so the
    in-memory tier starts empty and only the disk tier can help.
    Asserts the acceptance invariant: with a warm cache, the restart
    and the serving warm-up ladder compile ZERO programs live."""
    import shutil
    import tempfile

    import paddle_tpu.fluid as fluid
    from paddle_tpu import inference
    from paddle_tpu.fluid import compile_cache, layers, monitor, unique_name
    from paddle_tpu.inference import ServeConfig, Server

    cache_dir = tempfile.mkdtemp(prefix="bench_restart_cache_")
    model_dir = tempfile.mkdtemp(prefix="bench_restart_model_")
    env_prev = os.environ.get(compile_cache.ENV_DIR)
    os.environ[compile_cache.ENV_DIR] = cache_dir

    def hits_misses():
        return (
            monitor.counter("executor_compile_cache_disk_hit_total").value,
            monitor.counter("executor_compile_cache_disk_miss_total").value)

    def build_train():
        main, startup = fluid.Program(), fluid.Program()
        with unique_name.guard(), fluid.program_guard(main, startup):
            x = layers.data("x", shape=[64], dtype="float32")
            y = layers.data("y", shape=[1], dtype="float32")
            h = x
            for _ in range(4):
                h = layers.fc(h, 256, act="relu")
            loss = layers.reduce_mean(
                layers.square_error_cost(layers.fc(h, 1), y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(32, 64).astype(np.float32),
            "y": rng.rand(32, 1).astype(np.float32)}

    def one_restart():
        """Build + init + first step: the whole downtime window a
        respawned worker pays before training resumes."""
        t0 = time.perf_counter()
        main, startup, loss = build_train()
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            lv = float(np.asarray(lv))
        return time.perf_counter() - t0, lv

    try:
        h0, m0 = hits_misses()
        t_cold, loss_cold = one_restart()
        h1, m1 = hits_misses()
        t_warm, loss_warm = one_restart()
        h2, m2 = hits_misses()
        assert m1 - m0 == 2 and h1 == h0, (
            "cold restart: want 2 disk misses (startup+main), "
            "got %d misses / %d hits" % (m1 - m0, h1 - h0))
        assert h2 - h1 == 2 and m2 == m1, (
            "warm restart compiled live: %d hits / %d misses "
            "(want 2 / 0)" % (h2 - h1, m2 - m1))
        assert loss_warm == loss_cold, (
            "deserialized executable diverged: %r vs %r"
            % (loss_cold, loss_warm))

        # serving cold-start: save a model once, then register it on
        # two fresh Servers — the second warm-up ladder must be served
        # entirely from disk
        smain, sstartup = fluid.Program(), fluid.Program()
        with unique_name.guard(), fluid.program_guard(smain, sstartup):
            x = layers.data("x", shape=[32], dtype="float32")
            prob = layers.softmax(layers.fc(layers.fc(
                x, 64, act="relu"), 8))
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(sstartup)
            fluid.io.save_inference_model(model_dir, ["x"], [prob], exe,
                                          main_program=smain)
        cfg = ServeConfig(max_batch_size=8)
        ladder = len(cfg.ladder())
        exemplar = {"x": np.zeros((1, 32), np.float32)}

        def one_register():
            pred = inference.create_predictor(inference.Config(model_dir))
            t0 = time.perf_counter()
            with Server() as srv:
                srv.register("m", pred, config=cfg, warmup_feed=exemplar)
                return time.perf_counter() - t0

        h0, m0 = hits_misses()
        t_serve_cold = one_register()
        h1, m1 = hits_misses()
        t_serve_warm = one_register()
        h2, m2 = hits_misses()
        assert m1 - m0 == ladder and h1 == h0, (
            "cold register: want %d disk misses, got %d misses / %d "
            "hits" % (ladder, m1 - m0, h1 - h0))
        assert h2 - h1 == ladder and m2 == m1, (
            "warm register compiled live: %d hits / %d misses "
            "(want %d / 0)" % (h2 - h1, m2 - m1, ladder))
    finally:
        if env_prev is None:
            os.environ.pop(compile_cache.ENV_DIR, None)
        else:
            os.environ[compile_cache.ENV_DIR] = env_prev
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(model_dir, ignore_errors=True)

    load_hist = monitor.get_metric("compile_cache_load_seconds")
    return {
        "restart_cold_to_first_step_seconds": round(t_cold, 3),
        "restart_warm_to_first_step_seconds": round(t_warm, 3),
        "restart_speedup": round(t_cold / max(t_warm, 1e-9), 3),
        "restart_register_cold_seconds": round(t_serve_cold, 3),
        "restart_register_warm_seconds": round(t_serve_warm, 3),
        "restart_register_speedup":
            round(t_serve_cold / max(t_serve_warm, 1e-9), 3),
        "restart_ladder_size": ladder,
        "restart_cache_load_seconds_sum": round(load_hist.sum, 3)
        if load_hist is not None else 0.0,
    }


def monitor_summary():
    """Framework-counter sub-dict for the JSON line (fluid/monitor.py):
    the same counters a production scrape would see, so BENCH_r0x.json
    captures executor/compile-cache behavior alongside throughput."""
    from paddle_tpu.fluid import monitor

    hits = monitor.counter("executor_compile_cache_hit_total").value
    misses = monitor.counter("executor_compile_cache_miss_total").value
    run_hist = monitor.get_metric("executor_run_seconds")
    fetch_hist = monitor.get_metric("executor_fetch_sync_seconds")
    dec_hist = monitor.get_metric("decode_step_seconds")
    dec_cache = monitor.get_metric("decode_cache_tokens")
    return {
        "executor_run_count": monitor.counter("executor_run_total").value,
        "compile_cache_hits": hits,
        "compile_cache_misses": misses,
        "compile_cache_hit_ratio": round(hits / max(1, hits + misses), 4),
        # persistent disk tier (fluid/compile_cache.py): restarts and
        # serving cold-starts that deserialized instead of compiling
        "compile_cache_disk_hits": monitor.counter(
            "executor_compile_cache_disk_hit_total").value,
        "compile_cache_disk_misses": monitor.counter(
            "executor_compile_cache_disk_miss_total").value,
        "compile_cache_quarantined": monitor.counter(
            "compile_cache_quarantined_total").value,
        "compile_cache_evicted": monitor.counter(
            "compile_cache_evicted_total").value,
        "executor_run_seconds_sum": round(run_hist.sum, 3)
        if run_hist is not None else 0.0,
        "batched_run_count":
            monitor.counter("executor_batched_run_total").value,
        "batched_iters_total":
            monitor.counter("executor_batched_iters_total").value,
        "fetch_sync_count": fetch_hist.count
        if fetch_hist is not None else 0,
        "fetch_sync_seconds_sum": round(fetch_hist.sum, 3)
        if fetch_hist is not None else 0.0,
        "window_overlap_hits":
            monitor.counter("executor_window_overlap_hit_total").value,
        "window_overlap_misses":
            monitor.counter("executor_window_overlap_miss_total").value,
        # decode fast path: steps climb, compile_cache_misses don't — the
        # "no per-token retrace" invariant is readable straight off the
        # JSON line
        "decode_steps_total":
            monitor.counter("decode_steps_total").value,
        "decode_cache_tokens": dec_cache.value
        if dec_cache is not None else 0.0,
        "decode_step_seconds_sum": round(dec_hist.sum, 3)
        if dec_hist is not None else 0.0,
        # long-context tier: ring hop count climbs once per traced ring
        # pass (n_shards - 1 each); the gauge holds the last traced
        # sequence-shard count
        "attn_ring_hops_total":
            monitor.counter("attn_ring_hops_total").value,
        "attn_seq_shards": monitor.gauge("attn_seq_shards").value,
        # serving tier: coalescing + admission across ALL hosted models
        # (the per-model labeled series stay in dump_prometheus)
        "serving_requests_total": _sum_labeled("serving_requests_total"),
        "serving_batches_total": _sum_labeled("serving_batches_total"),
        "serving_shed_total": _sum_labeled("serving_shed_total"),
        "decode_slot_joins_total":
            monitor.counter("decode_slot_join_total").value,
        "decode_slot_retires_total":
            monitor.counter("decode_slot_retire_total").value,
        "decode_slot_scatter_dispatches_total":
            monitor.counter("decode_slot_scatter_dispatch_total").value,
        # paged decode engine: page pool churn, prefix-cache behavior,
        # and the Pallas paged-kernel dispatch count (0 on the gather-
        # dense fallback path)
        "decode_pages_allocated_total":
            monitor.counter("decode_pages_allocated_total").value,
        "decode_pages_freed_total":
            monitor.counter("decode_pages_freed_total").value,
        "decode_pages_shared_total":
            monitor.counter("decode_pages_shared_total").value,
        "decode_prefix_hits_total":
            monitor.counter("decode_prefix_hit_total").value,
        "decode_prefix_misses_total":
            monitor.counter("decode_prefix_miss_total").value,
        "attn_paged_kernel_dispatches_total":
            monitor.counter("attn_paged_kernel_dispatch_total").value,
        # speculative decoding: mean tokens emitted per target verify
        # dispatch (1.0 = speculation never helps; k = always accepts)
        "decode_spec_verify_steps":
            _hist_count("decode_spec_accepted_tokens"),
        "decode_spec_accepted_tokens_total":
            _hist_sum("decode_spec_accepted_tokens"),
        "decode_spec_accepted_per_step": _hist_mean(
            "decode_spec_accepted_tokens"),
        # sparse embedding engine: residency/prefetch behavior summed
        # across ALL tables (per-table labeled series stay in
        # dump_prometheus)
        "embedding_prefetch_hit_total":
            _sum_labeled("embedding_prefetch_hit_total"),
        "embedding_prefetch_miss_total":
            _sum_labeled("embedding_prefetch_miss_total"),
        "embedding_evictions_total":
            _sum_labeled("embedding_evictions_total"),
        # telemetry plane state, so a BENCH_SERVE/BENCH_FLEET p50 in the
        # JSON history is comparable against runs with tracing on/off
        # (the acceptance bar: default-sampled tracing within noise)
        "telemetry": _telemetry_summary(),
    }


def _telemetry_summary():
    from paddle_tpu import telemetry

    if not telemetry.enabled():
        return {"enabled": False}
    return {
        "enabled": True,
        "sample": float(os.environ.get(telemetry.ENV_SAMPLE, 1.0) or 1.0),
        "spans_recorded": len(telemetry.snapshot()),
        "spans_dropped": telemetry.dropped_span_count(),
    }


def _sum_labeled(name):
    """Sum a counter across every label set it was registered under."""
    from paddle_tpu.fluid import monitor

    return monitor.sum_labeled(name)


def _hist_count(name):
    from paddle_tpu.fluid import monitor

    h = monitor.get_metric(name)
    return h.count if h is not None else 0


def _hist_sum(name):
    from paddle_tpu.fluid import monitor

    h = monitor.get_metric(name)
    return round(h.sum, 3) if h is not None else 0.0


def _hist_mean(name):
    from paddle_tpu.fluid import monitor

    h = monitor.get_metric(name)
    if h is None or not h.count:
        return 0.0
    return round(h.sum / h.count, 3)


def bench_smoke():
    """``bench.py --smoke``: two tiny step-batched windows through the
    FULL async pipeline — py_reader feeds, background window prefetch,
    async fetch handles — on CPU in seconds, no TPU needed. Asserts the
    pipeline invariants (second window is an overlap hit, zero fetch
    syncs before ``.numpy()``, finite decoupled losses) and prints the
    same one-line JSON shape as the real bench."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if ("jax" not in sys.modules
            and "xla_force_host_platform_device_count" not in _flags):
        # the pipeline smoke leg wants a 2-stage mesh; harmless for the
        # rest (every other leg shards or replicates transparently)
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor

    monitor.reset()
    B, D, K = 8, 4, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = layers.py_reader(capacity=8, shapes=[[B, D], [B, 1]],
                                  dtypes=["float32", "float32"])
        x, y = layers.read_file(reader)
        pred = layers.fc(x, 1, name="smoke_fc")
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(0)
    batches = [(rng.rand(B, D).astype(np.float32),
                rng.rand(B, 1).astype(np.float32)) for _ in range(2 * K)]
    reader.decorate_tensor_provider(lambda: iter(batches))
    exe = fluid.Executor()
    t0 = time.perf_counter()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        reader.start()
        handles = []
        for _ in range(2):
            (h,) = exe.run(main, fetch_list=[loss], iters=K,
                           fetch_mode="async", prefetch=True)
            handles.append(h)
        syncs_before = monitor.get_metric(
            "executor_fetch_sync_seconds").count
        losses = [h.numpy().ravel().tolist() for h in handles]
    exe.close()
    assert syncs_before == 0, (
        "async windows synced %d time(s) before .numpy()" % syncs_before)
    assert all(np.isfinite(np.asarray(l)).all() for l in losses), losses
    hits = monitor.counter("executor_window_overlap_hit_total").value
    assert hits >= 1, "window 2 did not consume the prefetched window"

    # tiny KV-cache decode loop (CPU): the (prefill, decode) pair must
    # compile exactly twice and a repeat generation must not retrace —
    # the fast path can't silently rot out of --smoke coverage
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.models import transformer

    with dygraph.guard():
        model = transformer.Transformer.tiny()
        sess = transformer.build_decode_session(
            model, batch_size=2, src_len=6, prompt_len=4,
            cache_capacity=16, end_id=1)
        rng = np.random.RandomState(1)
        src = rng.randint(2, 512, (2, 6)).astype(np.int64)
        prompt = rng.randint(2, 512, (2, 4)).astype(np.int64)
        plens = np.array([4, 3], np.int64)
        m0 = monitor.counter("executor_compile_cache_miss_total").value
        toks, _ = sess.generate(src, prompt, plens, 6)
        m1 = monitor.counter("executor_compile_cache_miss_total").value
        toks2, _ = sess.generate(src, prompt, plens, 6)
        m2 = monitor.counter("executor_compile_cache_miss_total").value

        # speculative smoke: a full-depth self-draft over the same
        # session must cost exactly two extra compiles (draft + verify)
        # and reproduce the baseline tokens bit-for-bit
        spec_hist = monitor.get_metric("decode_spec_accepted_tokens")
        sc0, ss0 = spec_hist.count, spec_hist.sum
        spec = transformer.build_speculative_session(
            model, sess, k=3, draft_layers=len(model.dec_layers))
        spec_toks, _ = spec.generate(src, prompt, plens, 6)
        m3 = monitor.counter("executor_compile_cache_miss_total").value
        spec_acc = (spec_hist.sum - ss0) / max(1, spec_hist.count - sc0)

        # paged smoke: the block-pool engine through join/step must
        # cost exactly two compiles (batch-1 prefill + paged decode)
        # and emit the dense baseline's tokens per slot
        paged = transformer.build_paged_decode_session(
            model, batch_size=2, src_len=6, prompt_len=4,
            cache_capacity=16, end_id=1, page_tokens=4)
        paged_done = {}
        for b in range(2):
            pslot, pdone = paged.join(src[b], prompt[b],
                                      prompt_len=int(plens[b]),
                                      max_new_tokens=6)
            if pdone is not None:
                paged_done[pslot] = pdone[0]
        while paged.active_count:
            for pslot, ptoks, _pfin in paged.step():
                paged_done[pslot] = ptoks
        m4 = monitor.counter("executor_compile_cache_miss_total").value
    assert m1 - m0 == 2, "decode smoke: %d compiles, want 2" % (m1 - m0)
    assert m2 == m1, "decode smoke: repeat generation retraced"
    assert (toks == toks2).all(), "decode smoke: non-deterministic"
    assert m3 - m2 == 2, (
        "spec smoke: %d compiles, want 2 (draft + verify)" % (m3 - m2))
    assert (spec_toks == toks).all(), (
        "spec smoke: speculative tokens diverged from dense baseline")
    assert m4 - m3 == 2, (
        "paged smoke: %d compiles, want 2 (prefill1 + paged decode)"
        % (m4 - m3))
    for b in range(2):
        _pt = np.asarray(paged_done[b])
        assert np.array_equal(_pt, toks[b][:_pt.size]), (
            "paged smoke: slot %d tokens diverged from dense" % b)

    # tiny embedding loop: DeepFM with its big table host-offloaded at a
    # budget far under the vocabulary — admissions, evictions, and the
    # prefetch overlap path must all fire on CPU in a couple of seconds
    from paddle_tpu import embedding
    from paddle_tpu.fluid import unique_name
    from paddle_tpu.models import deepfm

    embedding.reset_tables()
    try:
        ecfg = deepfm.DeepFMConfig(sparse_feature_dim=640, num_fields=4,
                                   num_dense=3, embedding_size=4,
                                   fc_sizes=(16,))
        embedding.HostEmbeddingTable(
            "fm_emb", num_rows=ecfg.sparse_feature_dim,
            dim=ecfg.embedding_size, resident_budget=64, seed=7)
        with unique_name.guard():
            emain, estartup, eloss, _ = deepfm.build_train_program(
                ecfg, residence="host")
        eexe = fluid.Executor()
        feeds = [deepfm.synthetic_batch(ecfg, 8, seed=i) for i in range(5)]
        with fluid.scope_guard(fluid.Scope()):
            eexe.run(estartup)
            embed_losses = []
            for i, f in enumerate(feeds):
                (lv,) = eexe.run(emain, feed=f, fetch_list=[eloss])
                embed_losses.append(float(np.asarray(lv)))
                if i + 1 < len(feeds):
                    embedding.prefetch(emain, feeds[i + 1])
        assert all(np.isfinite(embed_losses)), embed_losses
        embed_hits = _sum_labeled("embedding_prefetch_hit_total")
        embed_evictions = _sum_labeled("embedding_evictions_total")
        assert embed_hits > 0, "embedding smoke: prefetch never hit"
        assert embed_evictions > 0, "embedding smoke: no evictions"
    finally:
        embedding.reset_tables()

    # tiny serving loop: 8 client threads through the dynamic batcher —
    # every future must resolve and the stream must coalesce
    serve = bench_serve(n_clients=8, per_client=2, max_batch_size=4,
                        max_queue_delay_ms=2.0, max_req_rows=2)
    assert serve["serve_batches"] < serve["serve_requests"], (
        "serve smoke: no coalescing happened")

    # tiny fleet loop: coord + one in-process replica + router + client
    # — registration via lease, routed traffic, graceful drain; the
    # serving-fleet wiring can't silently rot out of --smoke coverage
    import tempfile as _tf

    from paddle_tpu.distributed.coordination import CoordServer
    from paddle_tpu.serving import FleetClient, Replica, Router

    fleet_dir = _fleet_model_dir(_tf.mkdtemp(prefix="bench_smoke_fleet_"),
                                 prelower=False)
    fcoord = CoordServer().start()
    faddr = "%s:%d" % (fcoord.host, fcoord.port)
    frep = Replica(_fleet_spec(fleet_dir), coord_addr=faddr,
                   replica_id="smoke0", lease_ttl=5.0,
                   stats_interval=0.1).start()
    frouter = Router(coord_addr=faddr, refresh_interval=0.1).start()
    fleet_routed0 = _sum_labeled("fleet_routed_total")
    try:
        fcli = FleetClient("%s:%d" % (frouter.host, frouter.port))
        frng = np.random.RandomState(2)
        for _ in range(8):
            fx = frng.rand(frng.randint(1, 5), 32).astype(np.float32)
            fout = fcli.submit("fc", {"x": fx}, deadline_ms=10000)
            assert fout[0].shape == (fx.shape[0], 8)
        fcli.close()
    finally:
        frouter.close()
        frep.drain(timeout=10)
        fcoord.stop()
    fleet_routed = _sum_labeled("fleet_routed_total") - fleet_routed0
    assert fleet_routed == 8, (
        "fleet smoke: %d/8 requests routed" % fleet_routed)

    # coordinator crash + recovery under fleet load (tiny closed loop,
    # same model dir): zero requests lost, stale routing observed, WAL
    # replay brings the same port back at a bumped epoch
    coordrec = bench_coord_recovery(smoke=True, model_dir=fleet_dir)

    # persistent compile cache: a warm "restart" (fresh Executor,
    # rebuilt program, same cache dir) must deserialize BOTH programs
    # from disk and compile zero live — the restart fast path can't
    # silently rot out of --smoke coverage
    import shutil
    import tempfile

    from paddle_tpu.fluid import compile_cache

    cache_tmp = tempfile.mkdtemp(prefix="bench_smoke_cache_")
    cache_env_prev = os.environ.get(compile_cache.ENV_DIR)
    os.environ[compile_cache.ENV_DIR] = cache_tmp
    try:
        def _cc_restart():
            cmain, cstartup = fluid.Program(), fluid.Program()
            with unique_name.guard(), fluid.program_guard(cmain, cstartup):
                cx = layers.data("x", shape=[D], dtype="float32")
                cy = layers.data("y", shape=[1], dtype="float32")
                closs = layers.reduce_mean(layers.square_error_cost(
                    layers.fc(cx, 1, name="cc_fc"), cy))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(closs)
            cexe = fluid.Executor()
            with fluid.scope_guard(fluid.Scope()):
                cexe.run(cstartup)
                (clv,) = cexe.run(cmain, feed={"x": batches[0][0],
                                               "y": batches[0][1]},
                                  fetch_list=[closs])
                return float(np.asarray(clv))

        def _cc_counters():
            return (monitor.counter(
                        "executor_compile_cache_disk_hit_total").value,
                    monitor.counter(
                        "executor_compile_cache_disk_miss_total").value)

        ch0, cm0 = _cc_counters()
        cc_cold = _cc_restart()
        ch1, cm1 = _cc_counters()
        cc_warm = _cc_restart()
        ch2, cm2 = _cc_counters()
        assert cm1 - cm0 == 2 and ch1 == ch0, (
            "cache smoke cold: %d misses / %d hits, want 2 / 0"
            % (cm1 - cm0, ch1 - ch0))
        assert ch2 - ch1 == 2 and cm2 == cm1, (
            "cache smoke warm restart compiled live: %d hits / %d "
            "misses, want 2 / 0" % (ch2 - ch1, cm2 - cm1))
        assert cc_warm == cc_cold, (
            "cache smoke: deserialized executable diverged")
    finally:
        if cache_env_prev is None:
            os.environ.pop(compile_cache.ENV_DIR, None)
        else:
            os.environ[compile_cache.ENV_DIR] = cache_env_prev
        shutil.rmtree(cache_tmp, ignore_errors=True)

    # tiny 2-stage GPipe pipeline: one step through with_pipeline must
    # populate the schedule-shape gauge and the microbatch counter (the
    # 3D-parallelism observability contract — BENCH_PIPELINE=1 runs the
    # full bubble/weak-scaling leg)
    import jax as _jax

    pipe_stages = 2 if len(_jax.devices()) >= 2 else 1
    pipe_mb0 = monitor.counter("pipeline_microbatches_total").value
    ptraced, pstartup, ploss, pcompiled, pfeed_fn = _build_tower_pipeline(
        n_layers=2, n_stages=pipe_stages, trace_batch=2, seq_len=8,
        vocab=64, d_model=32, n_heads=2, d_inner=64, num_microbatches=2)
    pexe = fluid.Executor()
    with fluid.scope_guard(ptraced._scope):
        pexe.run(pstartup)
        (plv,) = pexe.run(pcompiled, feed=pfeed_fn(4), fetch_list=[ploss])
    assert np.isfinite(np.asarray(plv)).all()
    pipe_bubble = monitor.gauge("pipeline_bubble_fraction").value
    pipe_mb = monitor.counter("pipeline_microbatches_total").value - pipe_mb0
    assert pipe_bubble == (pipe_stages - 1) / (2 + pipe_stages - 1), (
        "pipeline smoke: bubble gauge %r != analytic" % pipe_bubble)
    assert pipe_mb == 2, (
        "pipeline smoke: microbatch counter moved %d, want 2" % pipe_mb)

    return {
        "serve_smoke_requests_per_sec": serve["serve_requests_per_sec"],
        "serve_smoke_mean_batch_occupancy":
            serve["serve_mean_batch_occupancy"],
        "metric": "smoke_async_pipeline_seconds",
        "value": round(time.perf_counter() - t0, 3),
        "unit": "seconds",
        "vs_baseline": None,
        "windows": 2,
        "iters_per_window": K,
        "window_losses": losses,
        "decode_smoke_tokens": int(toks.size),
        "decode_smoke_compile_misses": int(m1 - m0),
        "decode_spec_smoke_compile_misses": int(m3 - m2),
        "decode_spec_smoke_accepted_per_step": round(spec_acc, 2),
        "decode_paged_smoke_compile_misses": int(m4 - m3),
        "embed_smoke_steps": len(embed_losses),
        "embed_smoke_prefetch_hits": embed_hits,
        "embed_smoke_evictions": embed_evictions,
        "cache_smoke_disk_hits": int(ch2 - ch1),
        "cache_smoke_disk_misses": int(cm1 - cm0),
        "fleet_smoke_routed": fleet_routed,
        "coord_smoke_requests_lost": coordrec["coord_requests_lost"],
        "coord_smoke_stale_routed": coordrec["coord_stale_routed"],
        "coord_smoke_recovery_s": coordrec["coord_recovery_s"],
        "pipeline_smoke_bubble_fraction": pipe_bubble,
        "pipeline_smoke_microbatches": pipe_mb,
        "monitor": monitor_summary(),
    }


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        print(json.dumps(bench_smoke()))
        sys.exit(0)
    r = bench_bert()
    assert r["mfu"] <= 1.0, (
        "MFU %.3f > 1: either the peak table is wrong for this chip or the "
        "timing missed work" % r["mfu"])
    out = {
        "metric": "bert_base_mlm_train_tokens_per_sec",
        "value": r["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,
    }
    out.update(r)
    if os.environ.get("BENCH_LENET") == "1":
        out.update(bench_lenet())
    if os.environ.get("BENCH_RESNET") == "1":
        out.update(bench_resnet())
    if os.environ.get("BENCH_DEEPFM") == "1":
        out.update(bench_deepfm())
    if os.environ.get("BENCH_TRANSFORMER") == "1":
        out.update(bench_transformer())
    if os.environ.get("BENCH_PIPELINE") == "1":
        out.update(bench_pipeline())
    if os.environ.get("BENCH_DECODE") == "1":
        out.update(bench_transformer_decode())
    if os.environ.get("BENCH_DECODE_PROFILE") == "1":
        out.update(bench_decode_profile())
    if os.environ.get("BENCH_SERVE") == "1":
        out.update(bench_serve())
    if os.environ.get("BENCH_FLEET") == "1":
        out.update(bench_fleet())
    if os.environ.get("BENCH_COORD") == "1":
        out.update(bench_coord_recovery())
    if os.environ.get("BENCH_EMBED") == "1":
        out.update(bench_embedding())
    if os.environ.get("BENCH_RESTART") == "1":
        out.update(bench_restart())
    if os.environ.get("BENCH_MULTIHOST") == "1":
        out.update(bench_multihost())
    if os.environ.get("BENCH_LONGCTX") == "1":
        out.update(bench_longctx())
    if os.environ.get("BENCH_LONGSEQ") == "1":
        out.update(bench_longseq())
        out.update(bench_longseq(batch_size=4, seq_len=4096,
                                 prefix="longseq4k"))
        out.update(bench_longseq(batch_size=2, seq_len=8192,
                                 prefix="longseq8k"))
    out["monitor"] = monitor_summary()
    print(json.dumps(out))
