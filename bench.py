"""Benchmark entry: prints ONE JSON line with the headline metric.

Run on real TPU hardware by the driver. Flagship benchmark: BERT-base MLM
pretraining train-step throughput (BASELINE.md config 3 — the reference's
ERNIE/BERT Fleet workload), tokens/sec on one chip. ``vs_baseline`` is null:
the reference publishes no benchmark figures (BASELINE.md)."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def bench_bert(batch_size=128, seq_len=128, warmup=3, iters=10):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import bert

    import jax

    cfg = bert.BertConfig.base()
    main, startup, loss = bert.build_pretrain_program(cfg, seq_len=seq_len,
                                                      use_amp=True)
    exe = fluid.Executor()
    batch = bert.synthetic_batch(cfg, batch_size, seq_len)
    # pre-stage the batch on device (the DataLoader double-buffer path does
    # this during training; the chip may sit behind a slow host link)
    batch = {k: jax.device_put(v) for k, v in batch.items()}

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(max(warmup, 1)):  # >=1: compile before the clock
            (lv,) = exe.run(main, feed=batch, fetch_list=[loss],
                            return_numpy=False)
        jax.block_until_ready(lv)
        t0 = time.perf_counter()
        for _ in range(iters):
            # keep the loss as a device future: materializing a scalar
            # across a slow host link would serialize the pipeline (training
            # loops fetch metrics every N steps, not every step)
            (lv,) = exe.run(main, feed=batch, fetch_list=[loss],
                            return_numpy=False)
        jax.block_until_ready(lv)
        elapsed = time.perf_counter() - t0
        assert np.isfinite(np.asarray(lv)).all()
    return batch_size * seq_len * iters / elapsed


if __name__ == "__main__":
    tps = bench_bert()
    print(json.dumps({
        "metric": "bert_base_mlm_train_tokens_per_sec",
        "value": round(float(tps), 1),
        "unit": "tokens/sec",
        "vs_baseline": None,
    }))
